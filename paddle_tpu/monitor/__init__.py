"""paddle_tpu.monitor — unified training telemetry.

Four pillars (ISSUE 3 tentpole; see docs/OBSERVABILITY.md):

1. a structured **metrics registry** (:mod:`.metrics`): thread-safe
   Counter/Gauge/Histogram with labels, Prometheus text + append-only
   JSONL export, a process-global default registry plus
   :func:`scoped_registry` for tests;
2. **step-time instrumentation** in :class:`~paddle_tpu.jit.to_static.
   TrainStep` — ``TrainStep.stats()`` snapshots compiles/recompiles,
   eager-cache hit rates and (under ``FLAGS_monitor``) per-step
   wall/dispatch timings streamed into the registry;
3. **collective tracing** (:mod:`paddle_tpu.distributed.collective`):
   every eager collective records op/group/bytes/latency counters and a
   host-timeline RecordEvent;
4. the **NaN/Inf watchdog** (:mod:`.numerics`): eager post-step checks
   that name the first offending parameter/gradient and step index,
   AMP-GradScaler aware.

The registry is always importable and writable; the HOT paths only write
to it when ``FLAGS_monitor`` is set (zero-overhead default, pinned by
the write_count guard in tests/test_monitor.py).
"""

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,  # noqa: F401
                      get_registry, load_jsonl, scoped_registry)
from .numerics import (NaNWatchdog, NonFiniteError, all_finite,  # noqa: F401
                       check_numerics, first_nonfinite, nonfinite_entries)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_registry",
    "scoped_registry", "load_jsonl",
    "NaNWatchdog", "NonFiniteError", "all_finite", "check_numerics",
    "first_nonfinite", "nonfinite_entries",
    "enabled",
]


def enabled() -> bool:
    """True when ``FLAGS_monitor`` is set — hot paths consult this before
    writing per-step samples into the registry."""
    from ..core.flags import get_flag
    return bool(get_flag("monitor"))
