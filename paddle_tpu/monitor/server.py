"""Embedded admin/telemetry HTTP plane: ``/metrics`` · ``/healthz`` ·
``/statusz`` · ``/debug/*`` on a live process (ISSUE 14;
docs/OBSERVABILITY.md "Live telemetry plane").

Everything this repo's observability built so far — registry, traces,
SLO burn, flight recorder, program tables — was *post-hoc*: JSONL/JSON
dumps read by ``tools/monitor_report.py`` after the run ends. A serving
process in front of real traffic needs the pull-while-running half: a
scrape endpoint an operator points Prometheus at, health/readiness
wired to the engine's actual state machine, and the ability to grab a
profile or the trace ring from the LIVE process without restarting it.

:class:`AdminServer` is a stdlib ``http.server.ThreadingHTTPServer``
(no dependencies, one daemon accept thread + per-request handler
threads) started by the serving engine — and opt-in by ``TrainStep`` /
bench runs — when ``FLAGS_monitor_port`` is set:

==================  =======================================================
endpoint            payload
==================  =======================================================
``/metrics``        text exposition of the active registry. Content-
                    negotiated: an ``Accept: application/openmetrics-
                    text`` scrape gets the OpenMetrics page with
                    histogram exemplars rendered in the
                    ``# {trace_id="..."}`` suffix syntax (+ ``# EOF``);
                    plain scrapes get classic 0.0.4 text without
                    exemplars (whose parser would reject the suffix).
                    Each scrape also snapshots the registry into the
                    in-memory :class:`~.timeseries.TimeseriesRing`
``/healthz``        liveness: 200 while the process answers at all
``/readyz``         readiness: 200 only when EVERY registered readiness
                    provider reports ready; 503 with a structured JSON
                    reason body otherwise (the serving engine registers
                    draining / shedding / watchdog-tripped)
``/statusz``        one JSON page: environment fingerprint, full flags
                    snapshot, per-program FLOPs/HBM table, registered
                    status sections (engine occupancy, SLO burn, …) and
                    windowed per-second rates from the timeseries ring
``/debug/flight``   the flight-recorder document — byte-for-byte the
                    JSON a crash would dump (ring of step records,
                    events, fingerprint, attached trace section)
``/debug/trace``    retained + in-flight structured-trace span trees;
                    ``?format=perfetto`` returns the merged
                    chrome-trace/Perfetto timeline instead
``/debug/profile``  ``?seconds=N`` arms a profiler window on the live
                    process (host RecordEvent + eager-op timeline),
                    sleeps N seconds on the request thread, and returns
                    the chrome-trace JSON; 409 while another capture
                    (or a user profiler session) is active
==================  =======================================================

Zero-overhead contract: ``FLAGS_monitor_port`` unset (0, the default)
means :func:`maybe_start_from_flags` returns None after ONE flag read —
no thread, no socket, no registry series — pinned by test. When the
server IS on, each request increments ``monitor_http_requests_total``
(by endpoint) in the active registry.

Security: binds ``FLAGS_monitor_host`` = 127.0.0.1 by default. The
plane exposes flags, program tables and live profiles — widening the
bind address is an explicit operator decision (see the security note in
docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional
from urllib.parse import parse_qs, urlparse

from .flight_recorder import _json_safe_tree, get_flight_recorder
from .timeseries import TimeseriesRing

__all__ = ["AdminServer", "maybe_start_from_flags", "get_server",
           "stop_server", "PROFILE_MAX_SECONDS"]

#: upper clamp for /debug/profile?seconds=N — a scrape must never pin
#: the handler thread for minutes because of a typo'd query param
PROFILE_MAX_SECONDS = 60.0

#: default trailing window for the /statusz rates section (seconds)
STATUS_RATE_WINDOW_S = 60.0

#: thread-name prefix of every admin-plane thread — the zero-thread pin
#: in tests greps live thread names for this
THREAD_PREFIX = "ptpu-admin"

_profile_lock = threading.Lock()

#: sentinel a provider returns when its weakref'd subject was garbage
#: collected: the registration is PRUNED on the next read. Readiness
#: providers must use this (never None) for a dead subject — None
#: means "ready", and a collected engine silently reading as ready is
#: exactly the fail-open a load balancer must not see.
STALE = object()


class _Handler(BaseHTTPRequestHandler):
    # per-request handler; self.server is the _HTTPServer below, whose
    # .admin is the AdminServer

    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):     # stdlib default logs to
        pass                               # stderr per request — no

    def do_GET(self):                      # noqa: N802 (stdlib name)
        admin: "AdminServer" = self.server.admin
        parsed = urlparse(self.path)
        query = {k: v[-1] for k, v in parse_qs(parsed.query).items()}
        try:
            admin._count_request(parsed.path)
            admin._dispatch(self, parsed.path, query)
        except BrokenPipeError:
            pass                           # client went away mid-write
        except Exception as e:             # a handler bug must answer
            try:                           # 500, never kill the thread
                self._send(500, "application/json",
                           json.dumps({"error": repr(e)}).encode())
            except Exception:
                pass

    def _send(self, code: int, ctype: str, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class _HTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    admin: "AdminServer"

    def process_request(self, request, client_address):
        # stamp the per-request worker threads with the admin prefix so
        # the zero-thread overhead pin can account for every thread the
        # plane ever creates (ThreadingHTTPServer names them Thread-N)
        t = threading.Thread(
            target=self.process_request_thread,
            args=(request, client_address),
            name=f"{THREAD_PREFIX}-req",
            daemon=True)
        t.start()


class AdminServer:
    """One embedded admin plane. ``start()`` binds + spawns the accept
    thread; ``close()`` tears both down. ``registry=None`` resolves the
    ACTIVE registry per request (so ``scoped_registry`` tests and the
    process-global default both work)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry=None, ring: Optional[TimeseriesRing] = None,
                 clock=time.time):
        self._requested_port = int(port)
        self.host = host
        self._registry = registry
        self.ring = ring if ring is not None else TimeseriesRing()
        self.clock = clock
        self._httpd: Optional[_HTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        #: name -> callable() -> None (ready) | dict (not-ready reason)
        self._readiness: Dict[str, Callable[[], Optional[dict]]] = {}
        #: name -> callable() -> JSON-safe section (None = provider gone)
        self._status: Dict[str, Callable[[], Any]] = {}
        self._lock = threading.Lock()

    # -- lifecycle ----------------------------------------------------------
    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def url(self) -> Optional[str]:
        return (f"http://{self.host}:{self.port}"
                if self._httpd else None)

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "AdminServer":
        if self._httpd is not None:
            return self
        self._httpd = _HTTPServer((self.host, self._requested_port),
                                  _Handler)
        self._httpd.admin = self
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name=f"{THREAD_PREFIX}-http", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        httpd, thread = self._httpd, self._thread
        self._httpd = self._thread = None
        if httpd is not None:
            try:
                httpd.shutdown()
                httpd.server_close()
            except Exception:
                pass
        if thread is not None:
            thread.join(timeout=2.0)

    # -- provider registration ----------------------------------------------
    def register_readiness(self, name: str,
                           fn: Callable[[], Optional[dict]]) -> None:
        """``fn()`` returns None while ready, a JSON-safe dict
        explaining why not (it becomes the 503 body's reason), or
        :data:`STALE` when its subject no longer exists (the
        registration is then pruned — a weakref'd provider must never
        let a collected subject read as ready)."""
        with self._lock:
            self._readiness[name] = fn

    def unregister_readiness(self, name: str) -> None:
        with self._lock:
            self._readiness.pop(name, None)

    def register_status(self, name: str, fn: Callable[[], Any]) -> None:
        """``fn()`` returns a JSON-safe section for ``/statusz`` (None
        = the provider's subject is gone; the entry is dropped)."""
        with self._lock:
            self._status[name] = fn

    def unregister_status(self, name: str) -> None:
        with self._lock:
            self._status.pop(name, None)

    # -- request plumbing ---------------------------------------------------
    def registry(self):
        if self._registry is not None:
            return self._registry
        from .metrics import get_registry
        return get_registry()

    #: the label vocabulary of monitor_http_requests_total — anything
    #: else (scanners, misdirected probes, typos) folds into "other" so
    #: junk paths can never grow registry cardinality without bound
    _KNOWN_PATHS = frozenset((
        "/", "", "/metrics", "/healthz", "/readyz", "/statusz",
        "/debug/flight", "/debug/trace", "/debug/profile"))

    def _count_request(self, path: str) -> None:
        try:
            self.registry().counter(
                "monitor_http_requests_total",
                "admin-plane HTTP requests by endpoint").inc(
                path=path if path in self._KNOWN_PATHS else "other")
        except Exception:
            pass                   # telemetry about telemetry is
                                   # best-effort, never a 500

    def _dispatch(self, h: _Handler, path: str,
                  query: Dict[str, str]) -> None:
        if path == "/metrics":
            return self._metrics(h)
        if path == "/healthz":
            return h._send(200, "text/plain; charset=utf-8", b"ok\n")
        if path == "/readyz":
            return self._readyz(h)
        if path == "/statusz":
            return self._statusz(h, query)
        if path == "/debug/flight":
            return self._json(h, get_flight_recorder().doc(
                reason="admin_endpoint"))
        if path == "/debug/trace":
            return self._debug_trace(h, query)
        if path == "/debug/profile":
            return self._debug_profile(h, query)
        if path in ("/", ""):
            return self._json(h, {
                "endpoints": ["/metrics", "/healthz", "/readyz",
                              "/statusz", "/debug/flight",
                              "/debug/trace", "/debug/profile"]})
        h._send(404, "application/json",
                json.dumps({"error": f"no such endpoint {path!r}"}
                           ).encode())

    @staticmethod
    def _json(h: _Handler, doc: Any, code: int = 200) -> None:
        body = json.dumps(_json_safe_tree(doc), indent=1).encode()
        h._send(code, "application/json", body)

    # -- endpoints ----------------------------------------------------------
    def _metrics(self, h: _Handler) -> None:
        reg = self.registry()
        try:
            self.ring.snapshot(reg)    # scrapes ARE the rate clock
        except Exception:
            pass
        # content negotiation: exemplar suffixes are only legal in the
        # OpenMetrics format (which also requires the # EOF trailer) —
        # the classic text/plain 0.0.4 parser real Prometheus selects
        # from the Content-Type would reject them and fail the WHOLE
        # scrape, so the plain page ships without exemplars
        accept = h.headers.get("Accept", "")
        if "application/openmetrics-text" in accept:
            text = reg.to_prometheus(exemplars=True) + "# EOF\n"
            ctype = ("application/openmetrics-text; version=1.0.0; "
                     "charset=utf-8")
        else:
            text = reg.to_prometheus(exemplars=False)
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        h._send(200, ctype, text.encode())

    def _readyz(self, h: _Handler) -> None:
        with self._lock:
            providers = dict(self._readiness)
        reasons: Dict[str, dict] = {}
        stale = []
        for name, fn in providers.items():
            try:
                r = fn()
            except Exception as e:
                r = {"state": "provider-error", "error": repr(e)}
            if r is STALE:                 # subject collected: prune,
                stale.append(name)         # never read as "ready"
                continue
            if r is not None:
                reasons[name] = r
        for name in stale:
            self.unregister_readiness(name)
        if reasons:
            self._json(h, {"ready": False, "reasons": reasons},
                       code=503)
        else:
            self._json(h, {"ready": True,
                           "checks": sorted(set(providers) - set(stale))})

    def _statusz(self, h: _Handler, query: Dict[str, str]) -> None:
        reg = self.registry()
        try:
            self.ring.snapshot(reg)
        except Exception:
            pass
        try:
            window = float(query.get("window", STATUS_RATE_WINDOW_S))
        except ValueError:
            window = STATUS_RATE_WINDOW_S
        from ..core import flags as F
        from . import memory as monitor_memory
        doc: Dict[str, Any] = {
            "now": self.clock(),
            "fingerprint": get_flight_recorder().fingerprint(),
            "flags": {name: F.get_flag(name)
                      for name in sorted(F._REGISTRY)},
            "programs": {kind: pm.as_dict() for kind, pm in
                         monitor_memory.programs().items()},
            "rates": {"window_s": window,
                      "per_second": self.ring.rates(window_s=window)},
        }
        with self._lock:
            providers = dict(self._status)
        sections: Dict[str, Any] = {}
        stale = []
        for name, fn in providers.items():
            try:
                section = fn()
            except Exception as e:
                sections[name] = {"error": repr(e)}
                continue
            if section is None or section is STALE:
                stale.append(name)     # weakref'd subject collected
                continue
            sections[name] = section
        for name in stale:
            self.unregister_status(name)
        doc["sections"] = sections
        self._json(h, doc)

    def _debug_trace(self, h: _Handler, query: Dict[str, str]) -> None:
        from . import trace as trace_mod
        tracer = trace_mod.get_tracer()
        if query.get("format") == "perfetto":
            return self._json(h, trace_mod.perfetto_doc(
                tracer.snapshot(include_live=True)))
        self._json(h, {"format": 1, "dumped_at": self.clock(),
                       "traces": tracer.snapshot(include_live=True)})

    def _debug_profile(self, h: _Handler,
                       query: Dict[str, str]) -> None:
        try:
            seconds = float(query.get("seconds", 1.0))
        except ValueError:
            return self._json(h, {"error": "seconds must be a number"},
                              code=400)
        seconds = min(max(seconds, 0.01), PROFILE_MAX_SECONDS)
        from .. import profiler as prof
        if not _profile_lock.acquire(blocking=False):
            return self._json(
                h, {"error": "a profile capture is already running"},
                code=409)
        try:
            if prof._active[0]:
                return self._json(
                    h, {"error": "a profiler session is already "
                                 "active in this process"}, code=409)
            # host-side window only (RecordEvent spans + eager op
            # dispatches): it returns as one JSON body. Device XPlane
            # traces need a log_dir + TensorBoard — start_profiler
            # (log_dir=...) from the process itself for those.
            prof.start_profiler()
            try:
                time.sleep(seconds)
                doc = prof.chrome_trace_doc()
            finally:
                prof.stop_profiler()
        finally:
            _profile_lock.release()
        doc["captureSeconds"] = seconds
        self._json(h, doc)


# ---------------------------------------------------------------------------
# Flag-gated process-global server
# ---------------------------------------------------------------------------

_server: Optional[AdminServer] = None
_server_lock = threading.Lock()


def maybe_start_from_flags() -> Optional[AdminServer]:
    """Start (or return) the process-global admin server when
    ``FLAGS_monitor_port`` is set; None — after ONE flag read, zero
    allocations — when it is 0 (the default). ``-1`` binds an
    ephemeral OS-assigned port (tests / several processes per host;
    read it back from ``get_server().port``)."""
    from ..core.flags import get_flag
    port = int(get_flag("monitor_port") or 0)
    if port == 0:
        return None
    global _server
    with _server_lock:
        if _server is None or not _server.running:
            host = str(get_flag("monitor_host") or "127.0.0.1")
            srv = AdminServer(port=(0 if port < 0 else port), host=host)
            try:
                srv.start()
            except OSError as e:
                import warnings
                warnings.warn(
                    f"admin server failed to bind {host}:{port} "
                    f"({e}); telemetry plane disabled for this "
                    "process", RuntimeWarning)
                return None
            _server = srv
        return _server


def get_server() -> Optional[AdminServer]:
    """The process-global admin server, if one is running."""
    return _server


def stop_server() -> None:
    """Tear down the process-global server (tests / clean shutdown)."""
    global _server
    with _server_lock:
        if _server is not None:
            _server.close()
            _server = None
