"""Structured metrics registry: Counter / Gauge / Histogram with labels.

The framework-wide telemetry store (reference analogue: the per-op stat
tables of platform/profiler.cc plus the monitoring counters scattered
through fluid — here unified in one process-global registry, the way the
reference's device_tracer aggregates everything the timeline needs).

Design:
- three metric kinds — Counter (monotonic), Gauge (set-to-value),
  Histogram (bucketed observations with sum/count) — each supporting
  free-form string labels (``reg.counter("comm_bytes_total").inc(4096,
  op="all_reduce", group="dp")``);
- one process-global default registry (:func:`get_registry`) plus
  :func:`scoped_registry` for tests that need isolation;
- two export formats: Prometheus text exposition
  (:meth:`MetricsRegistry.to_prometheus`) and append-only JSONL
  (:meth:`MetricsRegistry.dump_jsonl`, rendered by
  ``tools/monitor_report.py``);
- every mutation bumps :attr:`MetricsRegistry.write_count`, which is how
  the zero-overhead guarantee of the monitor-off hot path is pinned in
  tests (no per-step registry writes unless ``FLAGS_monitor`` is on).

All operations are thread-safe (one RLock per registry; eager-op threads,
the DataLoader workers, and the async checkpoint thread may all write).
"""

from __future__ import annotations

import contextlib
import json
import re
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "get_registry", "scoped_registry", "load_jsonl",
    "load_registry_jsonl", "lint_exposition",
]

# Prometheus' default latency buckets (seconds), the right shape for both
# host-side step timings (ms..s) and eager dispatch (sub-ms).
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> _LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _escape(v: str) -> str:
    """Label-VALUE escaping per the Prometheus text exposition spec:
    backslash, double-quote and newline (in that order — escaping the
    escapes first, or a pre-escaped ``\\n`` would double)."""
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _escape_help(v: str) -> str:
    """HELP-text escaping: the spec escapes ONLY backslash and newline
    there (quotes are legal in prose). An unescaped newline would smear
    the rest of the help string into a bogus sample line — the
    unscrapeable-page failure mode the conformance lint exists for."""
    return v.replace("\\", r"\\").replace("\n", r"\n")


def _fmt_labels(key: _LabelKey, extra: Optional[Tuple[Tuple[str, str], ...]]
                = None) -> str:
    items = list(key) + list(extra or ())
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"' for k, v in items) + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, registry: "MetricsRegistry"):
        self.name = name
        self.help = help
        self._reg = registry
        self._lock = registry._lock
        self._series: Dict[_LabelKey, Any] = {}

    def samples(self) -> List[Tuple[Dict[str, str], Any]]:
        """[(labels_dict, value), ...] — value is a float for counter/gauge,
        a dict for histograms."""
        with self._lock:
            return [(dict(k), self._export(v))
                    for k, v in self._series.items()]

    def _export(self, v):
        return v

    def labels_seen(self) -> List[Dict[str, str]]:
        with self._lock:
            return [dict(k) for k in self._series]


class Counter(_Metric):
    """Monotonic counter (Prometheus semantics: only goes up)."""

    kind = "counter"

    def inc(self, value: float = 1.0, **labels) -> None:
        if value < 0:
            raise ValueError(f"counter {self.name}: negative increment "
                             f"{value} (use a Gauge)")
        k = _label_key(labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + value
            self._reg._write_count += 1

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class Gauge(_Metric):
    """Set-to-current-value metric (queue depth, loss scale, cache size)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._series[_label_key(labels)] = float(value)
            self._reg._write_count += 1

    def inc(self, value: float = 1.0, **labels) -> None:
        k = _label_key(labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + value
            self._reg._write_count += 1

    def dec(self, value: float = 1.0, **labels) -> None:
        self.inc(-value, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class Histogram(_Metric):
    """Bucketed observations with cumulative sum/count per label set."""

    kind = "histogram"

    def __init__(self, name, help, registry, buckets=None):
        super().__init__(name, help, registry)
        bs = tuple(sorted(buckets if buckets is not None else DEFAULT_BUCKETS))
        if not bs:
            raise ValueError(f"histogram {self.name}: needs >= 1 bucket")
        self.buckets = bs

    def observe(self, value: float, exemplar: Optional[str] = None,
                **labels) -> None:
        """Record one observation. ``exemplar`` (OpenMetrics exemplars:
        a trace_id) is attached to the bucket the value lands in — the
        newest exemplar per bucket wins — so a p99 bucket links to a
        CONCRETE trace (docs/OBSERVABILITY.md "Structured tracing")."""
        k = _label_key(labels)
        with self._lock:
            st = self._series.get(k)
            if st is None:
                st = {"counts": [0] * len(self.buckets), "sum": 0.0,
                      "count": 0}
                self._series[k] = st
            bucket = None
            for i, b in enumerate(self.buckets):
                if value <= b:
                    st["counts"][i] += 1
                    bucket = b
                    break
            st["sum"] += float(value)
            st["count"] += 1
            if exemplar is not None:
                # keyed by the bucket's upper bound ("+Inf" past the
                # last) — the join key readers use against `buckets`
                st.setdefault("exemplars", {})[
                    repr(float(bucket)) if bucket is not None
                    else "+Inf"] = {
                        "trace_id": str(exemplar),
                        "value": float(value), "ts": time.time()}
            self._reg._write_count += 1

    def _export(self, st) -> dict:
        # cumulative-`le` form, the shape both exporters serialize
        cum, acc = [], 0
        for b, c in zip(self.buckets, st["counts"]):
            acc += c
            cum.append([b, acc])
        out = {"count": st["count"], "sum": st["sum"], "buckets": cum}
        if st.get("exemplars"):
            out["exemplars"] = {le: dict(ex)
                                for le, ex in st["exemplars"].items()}
        return out

    def exemplars(self, **labels) -> Dict[str, dict]:
        """{le: {trace_id, value, ts}} for one label set (empty when no
        exemplar-carrying observation landed)."""
        with self._lock:
            st = self._series.get(_label_key(labels))
            return ({le: dict(ex)
                     for le, ex in st.get("exemplars", {}).items()}
                    if st else {})

    def count(self, **labels) -> int:
        with self._lock:
            st = self._series.get(_label_key(labels))
            return int(st["count"]) if st else 0

    def sum(self, **labels) -> float:
        with self._lock:
            st = self._series.get(_label_key(labels))
            return float(st["sum"]) if st else 0.0

    def mean(self, **labels) -> float:
        with self._lock:
            st = self._series.get(_label_key(labels))
            return float(st["sum"] / st["count"]) if st and st["count"] \
                else 0.0


class MetricsRegistry:
    """Named collection of metrics with get-or-create accessors."""

    def __init__(self, namespace: str = ""):
        self.namespace = namespace
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}
        self._write_count = 0

    # -- accessors ---------------------------------------------------------
    def _get_or_create(self, cls, name: str, help: str, **kw) -> _Metric:
        if self.namespace:
            name = f"{self.namespace}_{name}"
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, self, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets=None) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()

    @property
    def write_count(self) -> int:
        """Monotonic count of metric mutations — the overhead-guard probe:
        the monitor-off hot path must leave this unchanged per step."""
        with self._lock:
            return self._write_count

    # -- export ------------------------------------------------------------
    def snapshot(self) -> Dict[str, dict]:
        """{name: {type, help, samples: [(labels, value), ...]}} — values
        are plain python (floats / histogram dicts), safe to json-encode."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: {"type": m.kind, "help": m.help,
                         "samples": m.samples()} for m in metrics}

    def to_prometheus(self, exemplars: bool = False) -> str:
        """Prometheus text exposition format (v0.0.4). With
        ``exemplars=True``, histogram exemplars render in the
        OpenMetrics ``# {trace_id="..."}`` suffix syntax on the bucket
        they landed in — the link from a p99 bucket to a concrete
        structured trace. The suffix is only legal in OpenMetrics
        responses (classic text/plain parsers reject it and fail the
        whole page), so it is OFF by default and the admin server
        enables it only on Accept-negotiated scrapes. HELP text and
        label values are escaped per the exposition spec (a stray
        ``"`` or newline in a label must never produce an unscrapeable
        page); :func:`lint_exposition` checks the emitted grammar."""
        lines: List[str] = []
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]

        def exemplar_suffix(value: dict, le_key: str) -> str:
            if not exemplars:
                return ""
            ex = (value.get("exemplars") or {}).get(le_key)
            if not ex:
                return ""
            return (f' # {{trace_id="{_escape(str(ex["trace_id"]))}"}} '
                    f'{ex["value"]} {ex["ts"]:.3f}')

        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for labels, value in m.samples():
                key = _label_key(labels)
                if m.kind == "histogram":
                    for le, cum in value["buckets"]:
                        le_key = repr(float(le))
                        lines.append(
                            f"{m.name}_bucket"
                            f"{_fmt_labels(key, (('le', le_key),))}"
                            f" {cum}"
                            f"{exemplar_suffix(value, le_key)}")
                    lines.append(
                        f"{m.name}_bucket"
                        f"{_fmt_labels(key, (('le', '+Inf'),))}"
                        f" {value['count']}"
                        f"{exemplar_suffix(value, '+Inf')}")
                    lines.append(f"{m.name}_sum{_fmt_labels(key)} "
                                 f"{value['sum']}")
                    lines.append(f"{m.name}_count{_fmt_labels(key)} "
                                 f"{value['count']}")
                else:
                    lines.append(f"{m.name}{_fmt_labels(key)} {value}")
        return "\n".join(lines) + ("\n" if lines else "")

    # -- multi-host aggregation --------------------------------------------
    def _raw_metric(self, name: str, kind: str, help: str = "",
                    buckets=None) -> _Metric:
        """Get-or-create by FULL name (no namespace prefixing) — the
        merge/loader path, where incoming names are already final."""
        cls = {"counter": Counter, "gauge": Gauge,
               "histogram": Histogram}[kind]
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                kw = {"buckets": buckets} if kind == "histogram" else {}
                m = cls(name, help, self, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"merge brings {kind}")
            return m

    def merge(self, other: "MetricsRegistry",
              host: Optional[str] = None) -> None:
        """Fold ``other``'s series into this registry — the multi-host
        aggregation primitive behind ``tools/aggregate_metrics.py``
        (per-host JSONL registries → ONE exposition):

        - **counters** sum per label set (restart-safe: each process
          segment's total contributes once, so the merged series stays
          monotonic across a host restart);
        - **gauges** are last-write-wins; with ``host`` given, every
          incoming gauge series gains a ``host=<host>`` label so
          per-host values stay distinguishable instead of silently
          clobbering each other;
        - **histograms** merge bucket-wise (per-bucket counts, sum and
          count add). CONFLICTING bucket boundaries raise ValueError —
          adding counts across different ``le`` grids would silently
          corrupt every quantile read off the merged series;
        - histogram **exemplars** keep the newest per bucket (ts wins).
        """
        if other is self:
            return
        with other._lock:
            metrics = list(other._metrics.values())
        for m in metrics:
            with other._lock:
                series = {k: (dict(v) if isinstance(v, dict) else v)
                          for k, v in m._series.items()}
            mine = self._raw_metric(
                m.name, m.kind, m.help,
                buckets=getattr(m, "buckets", None))
            if m.kind == "histogram" and mine.buckets != m.buckets:
                raise ValueError(
                    f"histogram {m.name!r}: conflicting bucket "
                    f"boundaries {mine.buckets} vs {m.buckets} — "
                    "refusing to mis-merge (re-record with one bucket "
                    "layout, or rename the series)")
            with self._lock:
                for k, v in series.items():
                    if m.kind == "counter":
                        mine._series[k] = mine._series.get(k, 0.0) \
                            + float(v)
                    elif m.kind == "gauge":
                        key = (k if host is None else _label_key(
                            dict(dict(k), host=str(host))))
                        mine._series[key] = float(v)
                    else:
                        dst = mine._series.get(k)
                        if dst is None:
                            dst = {"counts": [0] * len(mine.buckets),
                                   "sum": 0.0, "count": 0}
                            mine._series[k] = dst
                        for i, c in enumerate(v["counts"]):
                            dst["counts"][i] += c
                        dst["sum"] += float(v["sum"])
                        dst["count"] += int(v["count"])
                        for le, ex in (v.get("exemplars") or {}).items():
                            cur = dst.setdefault("exemplars", {}).get(le)
                            if cur is None or ex.get("ts", 0.0) \
                                    >= cur.get("ts", 0.0):
                                dst["exemplars"][le] = dict(ex)
                    self._write_count += 1

    def dump_jsonl(self, path: str, extra: Optional[dict] = None) -> str:
        """Append one JSON line per (metric, label-set) sample.

        Append-only by design: successive dumps (per epoch, per bench run)
        accumulate; readers take the newest sample per (name, labels) —
        see tools/monitor_report.py. ``extra`` keys (epoch, tag, source)
        are merged into every line."""
        ts = time.time()
        base = dict(extra or {})
        with open(path, "a") as f:
            for name, info in self.snapshot().items():
                for labels, value in info["samples"]:
                    line = dict(base, ts=round(ts, 3), name=name,
                                type=info["type"], labels=labels)
                    if info["type"] == "histogram":
                        line.update(count=value["count"], sum=value["sum"],
                                    buckets=value["buckets"])
                        if value.get("exemplars"):
                            line["exemplars"] = value["exemplars"]
                    else:
                        line["value"] = value
                    f.write(json.dumps(line) + "\n")
        return path


def load_jsonl(path: str) -> List[dict]:
    """Parse a registry JSONL dump; skips malformed lines (a crashed
    writer must not make the whole record unreadable)."""
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(d, dict) and "name" in d:
                out.append(d)
    return out


def load_registry_jsonl(path: str,
                        registry: Optional[MetricsRegistry] = None) \
        -> MetricsRegistry:
    """Rebuild a :class:`MetricsRegistry` from a ``dump_jsonl`` stream.

    Gauges take the NEWEST sample per (name, labels); counters and
    histograms ACCUMULATE across restart segments *within* the file —
    an append-only stream whose value drops mid-file means the writer
    restarted, and the pre-restart segment's total still happened, so
    it contributes once and the loaded total stays monotonic (the same
    restart contract :meth:`MetricsRegistry.merge` gives across
    files). Histogram bucket boundaries must stay consistent within
    the file (and with any metric already in ``registry``) — a change
    raises rather than mis-merging; exemplars come from the newest
    segment. The input half of ``tools/aggregate_metrics.py``."""
    acc: Dict[Tuple[str, _LabelKey], dict] = {}
    for row in load_jsonl(path):
        name = row["name"]
        kind = row.get("type", "gauge")
        key = (name, tuple(sorted((k, str(v)) for k, v in
                                  (row.get("labels") or {}).items())))
        if kind == "counter":
            v = float(row.get("value", 0.0))
            st = acc.get(key)
            if st is None:
                acc[key] = {"kind": kind, "base": 0.0, "last": v}
            else:
                if v < st["last"]:           # restart: bank the segment
                    st["base"] += st["last"]
                st["last"] = v
        elif kind == "histogram":
            buckets = tuple(float(le)
                            for le, _ in (row.get("buckets") or []))
            if not buckets:
                continue               # empty histogram: nothing to keep
            counts, prev = [], 0
            for _, cum in row["buckets"]:
                counts.append(int(cum) - prev)
                prev = int(cum)
            seg = {"counts": counts, "sum": float(row.get("sum", 0.0)),
                   "count": int(row.get("count", 0)),
                   "exemplars": {le: dict(ex) for le, ex in
                                 (row.get("exemplars") or {}).items()}}
            st = acc.get(key)
            if st is None:
                acc[key] = {"kind": kind, "buckets": buckets,
                            "base": {"counts": [0] * len(buckets),
                                     "sum": 0.0, "count": 0},
                            "last": seg}
            else:
                if buckets != st["buckets"]:
                    raise ValueError(
                        f"histogram {name!r}: bucket boundaries "
                        f"changed mid-file in {path} ({st['buckets']} "
                        f"-> {buckets}) — refusing to mis-merge")
                if seg["count"] < st["last"]["count"]:
                    base = st["base"]
                    for i, c in enumerate(st["last"]["counts"]):
                        base["counts"][i] += c
                    base["sum"] += st["last"]["sum"]
                    base["count"] += st["last"]["count"]
                st["last"] = seg
        else:                              # gauge: newest wins
            acc[key] = {"kind": "gauge",
                        "value": float(row.get("value", 0.0))}
    reg = registry if registry is not None else MetricsRegistry()
    for (name, labels), st in sorted(acc.items()):
        kind = st["kind"]
        if kind == "histogram":
            m = reg._raw_metric(name, kind, buckets=list(st["buckets"]))
            if st["buckets"] != m.buckets:
                raise ValueError(
                    f"histogram {name!r}: {path} carries bucket "
                    f"boundaries {st['buckets']} but the registry "
                    f"already holds {m.buckets} — refusing to mis-merge")
            base, last = st["base"], st["last"]
            out = {"counts": [b + c for b, c in zip(base["counts"],
                                                    last["counts"])],
                   "sum": base["sum"] + last["sum"],
                   "count": base["count"] + last["count"]}
            if last["exemplars"]:
                out["exemplars"] = last["exemplars"]
            with reg._lock:
                m._series[labels] = out
                reg._write_count += 1
        else:
            m = reg._raw_metric(name, kind)
            value = (st["base"] + st["last"] if kind == "counter"
                     else st["value"])
            with reg._lock:
                m._series[labels] = value
                reg._write_count += 1
    return reg


# ---------------------------------------------------------------------------
# Exposition conformance lint
# ---------------------------------------------------------------------------

_L_METRIC_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_L_LABEL_NAME = r"[a-zA-Z_][a-zA-Z0-9_]*"
#: a quoted label value: no raw ", \ or newline; escapes limited to
#: \\ \" \n (the spec's set — anything else is an invalid escape)
_L_LABEL_VALUE = r'"(?:[^"\\\n]|\\["\\n])*"'
_L_LABELS = (rf"\{{{_L_LABEL_NAME}={_L_LABEL_VALUE}"
             rf"(?:,{_L_LABEL_NAME}={_L_LABEL_VALUE})*,?\}}")
_L_NUM = r"[+-]?(?:[0-9]+(?:\.[0-9]*)?(?:[eE][+-]?[0-9]+)?|\.[0-9]+(?:[eE][+-]?[0-9]+)?|Inf|NaN)"
#: OpenMetrics exemplar suffix: ` # {labels} value [ts]`
_L_EXEMPLAR = rf" # {_L_LABELS} {_L_NUM}(?: {_L_NUM})?"
_L_SAMPLE_RE = re.compile(
    rf"^({_L_METRIC_NAME})(?:{_L_LABELS})? {_L_NUM}"
    rf"(?: [+-]?[0-9]+)?(?:{_L_EXEMPLAR})?$")
_L_HELP_RE = re.compile(rf"^# HELP ({_L_METRIC_NAME}) (.*)$")
_L_TYPE_RE = re.compile(
    rf"^# TYPE ({_L_METRIC_NAME}) "
    r"(counter|gauge|histogram|summary|untyped)$")
def _bad_help_escape(text: str) -> bool:
    """True when HELP text contains an escape other than ``\\\\`` /
    ``\\n`` (scanned non-overlapping, so ``\\\\`` consumes both chars
    and a following literal char is not misread as an escape)."""
    return any(m.group(1) not in ("\\", "n")
               for m in re.finditer(r"\\(.?)", text))


def lint_exposition(text: str) -> List[str]:
    """Grammar-lint a Prometheus/OpenMetrics text page line by line;
    returns human-readable problems (empty list = scrapeable). This is
    the conformance gate behind ``/metrics`` and the exposition tests:
    every emitted line must parse as a HELP/TYPE comment or a sample
    (optionally exemplar-suffixed), label values must use only the
    spec's escape sequences, and sample names must belong to a
    TYPE-declared family (histogram samples may carry the
    ``_bucket``/``_sum``/``_count`` suffixes)."""
    problems: List[str] = []
    typed: Dict[str, str] = {}
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            mh = _L_HELP_RE.match(line)
            if mh:
                if _bad_help_escape(mh.group(2)):
                    problems.append(
                        f"line {i}: invalid escape in HELP text "
                        f"(only \\\\ and \\n are legal): {line!r}")
                continue
            mt = _L_TYPE_RE.match(line)
            if mt:
                name = mt.group(1)
                if name in typed:
                    problems.append(
                        f"line {i}: duplicate TYPE for {name!r}")
                typed[name] = mt.group(2)
                continue
            if line.startswith(("# HELP", "# TYPE")):
                problems.append(f"line {i}: malformed HELP/TYPE comment: "
                                f"{line!r}")
            continue                        # free-form comments are legal
        ms = _L_SAMPLE_RE.match(line)
        if ms is None:
            problems.append(f"line {i}: unparseable sample line: "
                            f"{line!r}")
            continue
        name = ms.group(1)
        if typed:
            family = name
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix) and \
                        name[:-len(suffix)] in typed:
                    family = name[:-len(suffix)]
                    break
            if family not in typed:
                problems.append(
                    f"line {i}: sample {name!r} has no TYPE declaration")
            elif family != name and typed[family] not in ("histogram",
                                                          "summary"):
                problems.append(
                    f"line {i}: {name!r} uses a histogram suffix but "
                    f"{family!r} is typed {typed[family]}")
    return problems


# ---------------------------------------------------------------------------
# Default + scoped registries
# ---------------------------------------------------------------------------

_default_registry = MetricsRegistry()
_registry_stack: List[MetricsRegistry] = []


def get_registry() -> MetricsRegistry:
    """The active registry: the innermost :func:`scoped_registry` if one is
    open, else the process-global default."""
    return _registry_stack[-1] if _registry_stack else _default_registry


@contextlib.contextmanager
def scoped_registry(registry: Optional[MetricsRegistry] = None) \
        -> Iterator[MetricsRegistry]:
    """Route :func:`get_registry` to a fresh (or given) registry for the
    with-block — test isolation without touching the process-global one."""
    reg = registry if registry is not None else MetricsRegistry()
    _registry_stack.append(reg)
    try:
        yield reg
    finally:
        _registry_stack.pop()
