"""Structured metrics registry: Counter / Gauge / Histogram with labels.

The framework-wide telemetry store (reference analogue: the per-op stat
tables of platform/profiler.cc plus the monitoring counters scattered
through fluid — here unified in one process-global registry, the way the
reference's device_tracer aggregates everything the timeline needs).

Design:
- three metric kinds — Counter (monotonic), Gauge (set-to-value),
  Histogram (bucketed observations with sum/count) — each supporting
  free-form string labels (``reg.counter("comm_bytes_total").inc(4096,
  op="all_reduce", group="dp")``);
- one process-global default registry (:func:`get_registry`) plus
  :func:`scoped_registry` for tests that need isolation;
- two export formats: Prometheus text exposition
  (:meth:`MetricsRegistry.to_prometheus`) and append-only JSONL
  (:meth:`MetricsRegistry.dump_jsonl`, rendered by
  ``tools/monitor_report.py``);
- every mutation bumps :attr:`MetricsRegistry.write_count`, which is how
  the zero-overhead guarantee of the monitor-off hot path is pinned in
  tests (no per-step registry writes unless ``FLAGS_monitor`` is on).

All operations are thread-safe (one RLock per registry; eager-op threads,
the DataLoader workers, and the async checkpoint thread may all write).
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "get_registry", "scoped_registry", "load_jsonl",
]

# Prometheus' default latency buckets (seconds), the right shape for both
# host-side step timings (ms..s) and eager dispatch (sub-ms).
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> _LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _escape(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _fmt_labels(key: _LabelKey, extra: Optional[Tuple[Tuple[str, str], ...]]
                = None) -> str:
    items = list(key) + list(extra or ())
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"' for k, v in items) + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, registry: "MetricsRegistry"):
        self.name = name
        self.help = help
        self._reg = registry
        self._lock = registry._lock
        self._series: Dict[_LabelKey, Any] = {}

    def samples(self) -> List[Tuple[Dict[str, str], Any]]:
        """[(labels_dict, value), ...] — value is a float for counter/gauge,
        a dict for histograms."""
        with self._lock:
            return [(dict(k), self._export(v))
                    for k, v in self._series.items()]

    def _export(self, v):
        return v

    def labels_seen(self) -> List[Dict[str, str]]:
        with self._lock:
            return [dict(k) for k in self._series]


class Counter(_Metric):
    """Monotonic counter (Prometheus semantics: only goes up)."""

    kind = "counter"

    def inc(self, value: float = 1.0, **labels) -> None:
        if value < 0:
            raise ValueError(f"counter {self.name}: negative increment "
                             f"{value} (use a Gauge)")
        k = _label_key(labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + value
            self._reg._write_count += 1

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class Gauge(_Metric):
    """Set-to-current-value metric (queue depth, loss scale, cache size)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._series[_label_key(labels)] = float(value)
            self._reg._write_count += 1

    def inc(self, value: float = 1.0, **labels) -> None:
        k = _label_key(labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + value
            self._reg._write_count += 1

    def dec(self, value: float = 1.0, **labels) -> None:
        self.inc(-value, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class Histogram(_Metric):
    """Bucketed observations with cumulative sum/count per label set."""

    kind = "histogram"

    def __init__(self, name, help, registry, buckets=None):
        super().__init__(name, help, registry)
        bs = tuple(sorted(buckets if buckets is not None else DEFAULT_BUCKETS))
        if not bs:
            raise ValueError(f"histogram {self.name}: needs >= 1 bucket")
        self.buckets = bs

    def observe(self, value: float, exemplar: Optional[str] = None,
                **labels) -> None:
        """Record one observation. ``exemplar`` (OpenMetrics exemplars:
        a trace_id) is attached to the bucket the value lands in — the
        newest exemplar per bucket wins — so a p99 bucket links to a
        CONCRETE trace (docs/OBSERVABILITY.md "Structured tracing")."""
        k = _label_key(labels)
        with self._lock:
            st = self._series.get(k)
            if st is None:
                st = {"counts": [0] * len(self.buckets), "sum": 0.0,
                      "count": 0}
                self._series[k] = st
            bucket = None
            for i, b in enumerate(self.buckets):
                if value <= b:
                    st["counts"][i] += 1
                    bucket = b
                    break
            st["sum"] += float(value)
            st["count"] += 1
            if exemplar is not None:
                # keyed by the bucket's upper bound ("+Inf" past the
                # last) — the join key readers use against `buckets`
                st.setdefault("exemplars", {})[
                    repr(float(bucket)) if bucket is not None
                    else "+Inf"] = {
                        "trace_id": str(exemplar),
                        "value": float(value), "ts": time.time()}
            self._reg._write_count += 1

    def _export(self, st) -> dict:
        # cumulative-`le` form, the shape both exporters serialize
        cum, acc = [], 0
        for b, c in zip(self.buckets, st["counts"]):
            acc += c
            cum.append([b, acc])
        out = {"count": st["count"], "sum": st["sum"], "buckets": cum}
        if st.get("exemplars"):
            out["exemplars"] = {le: dict(ex)
                                for le, ex in st["exemplars"].items()}
        return out

    def exemplars(self, **labels) -> Dict[str, dict]:
        """{le: {trace_id, value, ts}} for one label set (empty when no
        exemplar-carrying observation landed)."""
        with self._lock:
            st = self._series.get(_label_key(labels))
            return ({le: dict(ex)
                     for le, ex in st.get("exemplars", {}).items()}
                    if st else {})

    def count(self, **labels) -> int:
        with self._lock:
            st = self._series.get(_label_key(labels))
            return int(st["count"]) if st else 0

    def sum(self, **labels) -> float:
        with self._lock:
            st = self._series.get(_label_key(labels))
            return float(st["sum"]) if st else 0.0

    def mean(self, **labels) -> float:
        with self._lock:
            st = self._series.get(_label_key(labels))
            return float(st["sum"] / st["count"]) if st and st["count"] \
                else 0.0


class MetricsRegistry:
    """Named collection of metrics with get-or-create accessors."""

    def __init__(self, namespace: str = ""):
        self.namespace = namespace
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}
        self._write_count = 0

    # -- accessors ---------------------------------------------------------
    def _get_or_create(self, cls, name: str, help: str, **kw) -> _Metric:
        if self.namespace:
            name = f"{self.namespace}_{name}"
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, self, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets=None) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()

    @property
    def write_count(self) -> int:
        """Monotonic count of metric mutations — the overhead-guard probe:
        the monitor-off hot path must leave this unchanged per step."""
        with self._lock:
            return self._write_count

    # -- export ------------------------------------------------------------
    def snapshot(self) -> Dict[str, dict]:
        """{name: {type, help, samples: [(labels, value), ...]}} — values
        are plain python (floats / histogram dicts), safe to json-encode."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: {"type": m.kind, "help": m.help,
                         "samples": m.samples()} for m in metrics}

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (v0.0.4)."""
        lines: List[str] = []
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for labels, value in m.samples():
                key = _label_key(labels)
                if m.kind == "histogram":
                    for le, cum in value["buckets"]:
                        lines.append(
                            f"{m.name}_bucket"
                            f"{_fmt_labels(key, (('le', repr(float(le))),))}"
                            f" {cum}")
                    lines.append(
                        f"{m.name}_bucket"
                        f"{_fmt_labels(key, (('le', '+Inf'),))}"
                        f" {value['count']}")
                    lines.append(f"{m.name}_sum{_fmt_labels(key)} "
                                 f"{value['sum']}")
                    lines.append(f"{m.name}_count{_fmt_labels(key)} "
                                 f"{value['count']}")
                else:
                    lines.append(f"{m.name}{_fmt_labels(key)} {value}")
        return "\n".join(lines) + ("\n" if lines else "")

    def dump_jsonl(self, path: str, extra: Optional[dict] = None) -> str:
        """Append one JSON line per (metric, label-set) sample.

        Append-only by design: successive dumps (per epoch, per bench run)
        accumulate; readers take the newest sample per (name, labels) —
        see tools/monitor_report.py. ``extra`` keys (epoch, tag, source)
        are merged into every line."""
        ts = time.time()
        base = dict(extra or {})
        with open(path, "a") as f:
            for name, info in self.snapshot().items():
                for labels, value in info["samples"]:
                    line = dict(base, ts=round(ts, 3), name=name,
                                type=info["type"], labels=labels)
                    if info["type"] == "histogram":
                        line.update(count=value["count"], sum=value["sum"],
                                    buckets=value["buckets"])
                        if value.get("exemplars"):
                            line["exemplars"] = value["exemplars"]
                    else:
                        line["value"] = value
                    f.write(json.dumps(line) + "\n")
        return path


def load_jsonl(path: str) -> List[dict]:
    """Parse a registry JSONL dump; skips malformed lines (a crashed
    writer must not make the whole record unreadable)."""
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(d, dict) and "name" in d:
                out.append(d)
    return out


# ---------------------------------------------------------------------------
# Default + scoped registries
# ---------------------------------------------------------------------------

_default_registry = MetricsRegistry()
_registry_stack: List[MetricsRegistry] = []


def get_registry() -> MetricsRegistry:
    """The active registry: the innermost :func:`scoped_registry` if one is
    open, else the process-global default."""
    return _registry_stack[-1] if _registry_stack else _default_registry


@contextlib.contextmanager
def scoped_registry(registry: Optional[MetricsRegistry] = None) \
        -> Iterator[MetricsRegistry]:
    """Route :func:`get_registry` to a fresh (or given) registry for the
    with-block — test isolation without touching the process-global one."""
    reg = registry if registry is not None else MetricsRegistry()
    _registry_stack.append(reg)
    try:
        yield reg
    finally:
        _registry_stack.pop()
