"""Training goodput ledger — where every second of trainer wall-clock went.

The training-side counterpart of the serving observability plane
(docs/OBSERVABILITY.md): a :class:`GoodputLedger` attributes elapsed
wall-clock to exactly one of a small set of EXCLUSIVE buckets — the
Google ML-Goodput / MegaScale accounting model, where

    goodput% = productive_dispatch / elapsed

and every non-productive second is named (compile, data wait, checkpoint
stall, nonfinite rollback, restart gap, host other). Instrumentation
rides seams that already exist:

- ``TrainStep._compile_program``   → ``compile``
- ``TrainStep`` dispatch           → ``productive_dispatch`` (a dispatch
  that RAISES — e.g. the chaos ``collective.hang`` converted into
  ``CollectiveTimeoutError`` — is badput and folds into ``host_other``)
- the nonfinite watchdog trip path → ``nonfinite_rollback`` (the failed
  step's dispatch interval is re-attributed: a rolled-back update made
  no progress)
- ``CheckpointManager`` sync save / ``wait()`` → ``checkpoint_stall``
- dataloader ``next()``            → ``data_wait`` (the last wait is
  also attached as a ``data_wait`` span on the next ``train.step``
  trace)
- SIGTERM → resume                 → ``restart_gap`` (the ledger state
  persists in the CheckpointManager sidecar; ``resume()`` restores it
  and attributes the dead time between the final commit and the new
  process picking up)

``host_other`` is DERIVED — the residual ``elapsed - sum(measured)`` —
so the exhaustiveness invariant (bucket seconds sum to elapsed
wall-clock) holds by construction and is pinned by test. Exclusivity is
enforced by a monotonic cursor: overlapping/nested measures never
double-count a wall-clock second.

Zero-overhead contract (``FLAGS_train_goodput`` unset, the default):
:func:`measure` is one flag read and a no-op yield — no ledger object
is ever allocated (``GOODPUT_STATS['ledgers_allocated']`` stays 0, the
pin tests/test_goodput.py reads), no registry series appear, and the
compiled step program is bit-identical.

Per-layer model health (``FLAGS_train_health_every=N``) lives with the
ledger because both answer "is this run healthy": TrainStep compiles
per-layer grad-norm / param-norm / update-ratio f32 side-outputs into
the step program and publishes them through :func:`note_layer_health`;
the :class:`LayerHealthMonitor` EWMA spike detector here tail-marks the
step trace (``ANOMALY_REASONS`` entry ``health_spike``) and the last
health vector joins every flight-recorder dump.
"""

from __future__ import annotations

import contextlib
import math
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = [
    "BUCKETS", "BADPUT_BUCKETS", "GOODPUT_STATS", "GoodputLedger",
    "LayerHealthMonitor", "active", "active_ledger", "get_ledger",
    "measure", "note_layer_health", "last_layer_health", "reset",
    "statusz_section",
]

#: the exclusive wall-clock buckets; every elapsed second lands in
#: exactly one (docs/OBSERVABILITY.md "Training goodput & model health"
#: has the taxonomy table)
BUCKETS = ("productive_dispatch", "compile", "data_wait",
           "checkpoint_stall", "nonfinite_rollback", "restart_gap",
           "host_other")

#: everything that is not productive — the label set of
#: ``train_badput_seconds_total{bucket}``
BADPUT_BUCKETS = tuple(b for b in BUCKETS if b != "productive_dispatch")

#: allocation probe: the zero-overhead pin reads ledgers_allocated == 0
#: with FLAGS_train_goodput off (tests/test_goodput.py)
GOODPUT_STATS = {"ledgers_allocated": 0, "intervals_accounted": 0,
                 "reattributions": 0, "restores": 0}


class GoodputLedger:
    """Exclusive wall-clock accounting for one training process.

    Time is measured on ``time.perf_counter`` (interval arithmetic);
    persistence stamps ``time.time`` wall time so a restart can compute
    the cross-process gap. Thread-safe: the dataloader prefetcher and
    the training loop may account concurrently.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._start = time.perf_counter()
        # measured seconds THIS process; host_other only accrues here
        # via the on_error path of measure() — its main mass is the
        # derived residual added in snapshot()
        self._seconds: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        # restored from a previous incarnation's sidecar state (plus the
        # restart gap); snapshot() adds carry and live per bucket
        self._carry: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        self._carry_elapsed = 0.0
        self._restarts = 0
        # exclusivity cursor: no accounted interval may start before it
        self._cursor = self._start
        # (bucket, seconds) of the last closed interval — the nonfinite
        # watchdog re-attributes the failed step's dispatch through this
        self._last: Optional[Tuple[str, float]] = None
        # last closed data_wait interval (perf_counter t0/t1) awaiting
        # attachment as a span on the next train.step trace
        self._pending_data_wait: Optional[Tuple[float, float]] = None
        # per-bucket seconds already inc'd into the registry counter —
        # publish() emits deltas so the counter stays monotonic
        self._published: Dict[str, float] = {b: 0.0 for b in BUCKETS}

    # -- accounting --------------------------------------------------------
    def _account(self, bucket: str, t0: float, t1: float) -> None:
        if bucket not in self._seconds:
            raise ValueError(f"unknown goodput bucket {bucket!r}; "
                             f"expected one of {BUCKETS}")
        with self._lock:
            t0 = max(t0, self._cursor)
            if t1 <= t0:
                return
            dur = t1 - t0
            self._seconds[bucket] += dur
            self._cursor = t1
            self._last = (bucket, dur)
            GOODPUT_STATS["intervals_accounted"] += 1

    @contextlib.contextmanager
    def measure(self, bucket: str, on_error: Optional[str] = None):
        """Attribute the body's wall time to ``bucket`` (or to
        ``on_error`` when the body raises — a dispatch that died is not
        productive time). Nesting-safe: the exclusivity cursor clips any
        overlap with an interval already accounted."""
        t0 = time.perf_counter()
        try:
            yield
        except BaseException:
            self._account(on_error or bucket, t0, time.perf_counter())
            raise
        t1 = time.perf_counter()
        self._account(bucket, t0, t1)
        if bucket == "data_wait":
            with self._lock:
                self._pending_data_wait = (t0, t1)

    def reattribute_last(self, to_bucket: str) -> float:
        """Move the most recently closed interval into ``to_bucket`` —
        the nonfinite rollback path: the step's dispatch seconds were
        provisionally productive, but a rolled-back update made no
        progress. Returns the seconds moved (0.0 when there is no
        closed interval to move)."""
        if to_bucket not in self._seconds:
            raise ValueError(f"unknown goodput bucket {to_bucket!r}")
        with self._lock:
            if self._last is None:
                return 0.0
            bucket, dur = self._last
            if bucket != to_bucket:
                self._seconds[bucket] -= dur
                self._seconds[to_bucket] += dur
                GOODPUT_STATS["reattributions"] += 1
            self._last = (to_bucket, dur)
            return dur

    def pop_pending_data_wait(self) -> Optional[Tuple[float, float]]:
        """The last closed ``data_wait`` interval as perf_counter
        ``(t0, t1)`` — same clock domain as the structured tracer, so
        TrainStep can attach it as an explicit-timestamp span on the
        step trace. Cleared on read."""
        with self._lock:
            dw, self._pending_data_wait = self._pending_data_wait, None
            return dw

    # -- views -------------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-safe totals: per-bucket seconds (carry + live, with
        ``host_other`` absorbing the unmeasured residual), total elapsed
        and ``goodput_pct``. Sum of buckets == elapsed by construction."""
        with self._lock:
            now = time.perf_counter()
            live_elapsed = now - self._start
            measured = sum(self._seconds.values())
            residual = max(0.0, live_elapsed - measured)
            buckets = {b: self._seconds[b] + self._carry[b]
                       for b in BUCKETS}
            buckets["host_other"] += residual
            elapsed = live_elapsed + self._carry_elapsed
            good = buckets["productive_dispatch"]
            return {
                "elapsed_s": elapsed,
                "goodput_pct": (100.0 * good / elapsed) if elapsed > 0
                else 0.0,
                "restarts": self._restarts,
                "buckets": buckets,
            }

    # -- persistence (CheckpointManager sidecar) ---------------------------
    def state(self) -> dict:
        """Snapshot plus a wall-clock stamp — the JSON the
        CheckpointManager sidecar carries so ``goodput_pct`` survives
        SIGTERM → resume. Floats are kept at full precision: restore is
        bit-consistent."""
        s = self.snapshot()
        s["wall"] = time.time()
        s["version"] = 1
        return s

    def restore(self, state: dict) -> float:
        """Fold a previous incarnation's :meth:`state` into this
        ledger's carry and attribute the dead time since its wall stamp
        (minus what this process has already lived and accounted) to
        ``restart_gap``. Returns the gap seconds added."""
        with self._lock:
            live = time.perf_counter() - self._start
            gap = max(0.0, time.time() - float(state.get("wall", 0.0))
                      - live)
            if not state.get("wall"):
                gap = 0.0
            saved = state.get("buckets") or {}
            for b in BUCKETS:
                self._carry[b] += float(saved.get(b, 0.0))
            self._carry["restart_gap"] += gap
            self._carry_elapsed += float(state.get("elapsed_s", 0.0)) + gap
            self._restarts = int(state.get("restarts", 0)) + 1
            GOODPUT_STATS["restores"] += 1
            return gap

    # -- registry ----------------------------------------------------------
    def publish(self, registry=None) -> None:
        """Publish ``train_goodput_pct`` (gauge) and per-bucket
        ``train_badput_seconds_total`` counter DELTAS since the last
        publish — monotonic within a process, and the first publish
        after a restore carries the restored totals forward (the
        cross-restart aggregate stays monotonic under the registry's
        counter-merge convention)."""
        if registry is None:
            from .metrics import get_registry
            registry = get_registry()
        snap = self.snapshot()
        registry.gauge(
            "train_goodput_pct",
            "productive dispatch share of trainer wall-clock (the ML "
            "Goodput headline; buckets in train_badput_seconds_total)"
        ).set(snap["goodput_pct"])
        with self._lock:
            ctr = registry.counter(
                "train_badput_seconds_total",
                "non-productive trainer wall-clock by exclusive bucket "
                "(GoodputLedger)")
            for b in BADPUT_BUCKETS:
                delta = snap["buckets"][b] - self._published[b]
                if delta > 0:
                    ctr.inc(delta, bucket=b)
                    self._published[b] = snap["buckets"][b]


class LayerHealthMonitor:
    """EWMA spike detector over per-layer gradient norms.

    ``observe()`` takes the host-side health vector TrainStep publishes
    ({layer: {"grad_norm", "param_norm", "update_ratio"}}) and returns
    the layers whose grad norm spiked — value above ``factor`` × its
    EWMA after ``warmup`` observations, or non-finite at any point. The
    caller tail-marks the step trace (reason ``health_spike``) and
    bumps ``train_health_spikes_total``.
    """

    def __init__(self, alpha: float = 0.3, factor: float = 10.0,
                 warmup: int = 5):
        self.alpha = float(alpha)
        self.factor = float(factor)
        self.warmup = int(warmup)
        self._ewma: Dict[str, float] = {}
        self._count: Dict[str, int] = {}

    def observe(self, health: Dict[str, dict]) -> List[str]:
        spikes = []
        for layer, vals in health.items():
            g = float(vals.get("grad_norm", 0.0))
            if not math.isfinite(g):
                spikes.append(layer)
                continue
            n = self._count.get(layer, 0)
            e = self._ewma.get(layer)
            if (n >= self.warmup and e is not None
                    and g > self.factor * max(e, 1e-30)):
                spikes.append(layer)
                # a spike does not poison the baseline: the EWMA keeps
                # tracking so a genuine regime change re-arms after a
                # few steps instead of alerting forever
            self._ewma[layer] = g if e is None \
                else (1.0 - self.alpha) * e + self.alpha * g
            self._count[layer] = n + 1
        return spikes


# -- module-global plumbing (lazy: nothing allocates while the flag is
#    off — the zero-overhead pin) -----------------------------------------

_LEDGER: Optional[GoodputLedger] = None
_LAST_HEALTH: Optional[dict] = None
_HEALTH_PROVIDER_REGISTERED = False


def active() -> bool:
    """True when ``FLAGS_train_goodput`` is set."""
    from ..core.flags import get_flag
    return bool(get_flag("train_goodput"))


def get_ledger() -> Optional[GoodputLedger]:
    """The process ledger if one has been allocated (flag may have been
    turned off since); None otherwise. Never allocates."""
    return _LEDGER


def active_ledger() -> Optional[GoodputLedger]:
    """The process ledger when ``FLAGS_train_goodput`` is on (allocated
    lazily on first use), else None. The flag read comes FIRST: with the
    flag off this is one dict lookup and no allocation, ever."""
    if not active():
        return None
    global _LEDGER
    if _LEDGER is None:
        _LEDGER = GoodputLedger()
        GOODPUT_STATS["ledgers_allocated"] += 1
        from . import flight_recorder as _fr
        _fr.register_dump_provider("goodput", _dump_provider)
        # join a live admin plane if one is already up; TrainStep's
        # monitor_port path registers the section at server start too
        import sys
        srv_mod = sys.modules.get("paddle_tpu.monitor.server")
        if srv_mod is not None:
            srv = srv_mod.get_server()
            if srv is not None:
                srv.register_status("goodput", statusz_section)
    return _LEDGER


@contextlib.contextmanager
def measure(bucket: str, on_error: Optional[str] = None):
    """Module-level :meth:`GoodputLedger.measure` that is a no-op (one
    flag read) when ``FLAGS_train_goodput`` is off — the form every
    instrumentation seam uses."""
    led = active_ledger()
    if led is None:
        yield
        return
    with led.measure(bucket, on_error=on_error):
        yield


def statusz_section():
    """/statusz section provider: the ledger snapshot, or None (section
    skipped) while the flag is off / no ledger exists."""
    led = _LEDGER
    if led is None or not active():
        return None
    return led.snapshot()


def _dump_provider():
    """Flight-recorder attachment: goodput totals travel with every
    crash dump."""
    return statusz_section()


def note_layer_health(health: dict, step: Optional[int] = None) -> None:
    """Record the latest host-side per-layer health vector (TrainStep
    calls this at each publish cadence) and attach it to future
    flight-recorder dumps under ``layer_health``."""
    global _LAST_HEALTH, _HEALTH_PROVIDER_REGISTERED
    _LAST_HEALTH = {"step": step, "layers": health}
    if not _HEALTH_PROVIDER_REGISTERED:
        from . import flight_recorder as _fr
        _fr.register_dump_provider("layer_health", last_layer_health)
        _HEALTH_PROVIDER_REGISTERED = True


def last_layer_health() -> Optional[dict]:
    """The most recently published per-layer health vector
    (``{"step", "layers": {layer: {grad_norm, param_norm,
    update_ratio}}}``), or None."""
    return _LAST_HEALTH


def reset() -> None:
    """Drop all module state (tests; conftest autouse isolation)."""
    global _LEDGER, _LAST_HEALTH, _HEALTH_PROVIDER_REGISTERED
    _LEDGER = None
    _LAST_HEALTH = None
    _HEALTH_PROVIDER_REGISTERED = False
    for k in GOODPUT_STATS:
        GOODPUT_STATS[k] = 0
    import sys
    fr = sys.modules.get("paddle_tpu.monitor.flight_recorder")
    if fr is not None:
        fr._DUMP_PROVIDERS.pop("goodput", None)
        fr._DUMP_PROVIDERS.pop("layer_health", None)
