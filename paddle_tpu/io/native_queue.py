"""ctypes binding for the native blocking queue (C++ reader core).

reference parity: the Python face of LoDTensorBlockingQueue
(reference: operators/reader/blocking_queue.h + pybind bindings in
pybind/reader_py.cc). Here the binding is ctypes over a C ABI — no
pybind11 in the image — and the payloads are arbitrary byte buffers
(pickled batches / raw numpy), with the copy into C-heap memory freeing
the Python producer immediately.

The shared library is compiled on first use with g++ and cached next to
the source under a name that embeds the source hash — a changed
blocking_queue.cpp can never be served by a stale binary (and no binary
is ever checked into version control). `native_available()` reports
whether the toolchain produced a usable library (callers fall back to
queue.Queue).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_SRC = os.path.join(os.path.dirname(__file__), "_native",
                    "blocking_queue.cpp")


def _lib_path() -> Optional[str]:
    try:
        with open(_SRC, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:12]
    except OSError:
        return None          # source not shipped: fall back gracefully
    return os.path.join(os.path.dirname(__file__), "_native",
                        f"libblocking_queue-{digest}.so")


_lib_handle = None
_build_failed = False      # failures are cached: one compile attempt/process
_build_lock = threading.Lock()


class QueueClosed(Exception):
    pass


class QueueKilled(Exception):
    pass


def _build() -> Optional[ctypes.CDLL]:
    global _lib_handle, _build_failed
    with _build_lock:
        if _lib_handle is not None:
            return _lib_handle
        if _build_failed:
            return None
        lib_file = _lib_path()
        if lib_file is None:
            _build_failed = True
            return None
        if not os.path.exists(lib_file):
            # build to a private temp path and atomically publish, so a
            # concurrent/interrupted build can never leave a half-written
            # .so at the trusted final name
            tmp = f"{lib_file}.{os.getpid()}.tmp"
            try:
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-pthread", _SRC,
                     "-o", tmp],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp, lib_file)
            except (subprocess.SubprocessError, FileNotFoundError, OSError):
                _build_failed = True
                return None
            finally:
                if os.path.exists(tmp):
                    try:
                        os.remove(tmp)
                    except OSError:
                        pass
            # sweep caches of older source revisions (incl. the legacy
            # un-hashed name)
            import glob
            for old in glob.glob(os.path.join(
                    os.path.dirname(lib_file), "libblocking_queue*.so")):
                if old != lib_file:
                    try:
                        os.remove(old)
                    except OSError:
                        pass
        try:
            lib = ctypes.CDLL(lib_file)
        except OSError:
            _build_failed = True
            return None
        lib.pq_create.restype = ctypes.c_void_p
        lib.pq_create.argtypes = [ctypes.c_size_t]
        lib.pq_destroy.argtypes = [ctypes.c_void_p]
        lib.pq_send.restype = ctypes.c_int
        lib.pq_send.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_size_t]
        lib.pq_receive.restype = ctypes.POINTER(ctypes.c_ubyte)
        lib.pq_receive.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_size_t),
                                   ctypes.c_long,
                                   ctypes.POINTER(ctypes.c_int)]
        lib.pq_free.argtypes = [ctypes.POINTER(ctypes.c_ubyte)]
        lib.pq_close.argtypes = [ctypes.c_void_p]
        lib.pq_kill.argtypes = [ctypes.c_void_p]
        lib.pq_size.restype = ctypes.c_size_t
        lib.pq_size.argtypes = [ctypes.c_void_p]
        lib.pq_closed.restype = ctypes.c_int
        lib.pq_closed.argtypes = [ctypes.c_void_p]
        _lib_handle = lib
        return lib


def native_available() -> bool:
    return _build() is not None


class NativeBlockingQueue:
    """Bounded blocking byte queue over the C++ core."""

    def __init__(self, capacity: int = 8):
        lib = _build()
        if lib is None:
            raise RuntimeError("native blocking queue unavailable "
                               "(g++ build failed)")
        self._lib = lib
        self._q = lib.pq_create(capacity)
        if not self._q:
            raise ValueError("capacity must be > 0")

    def put(self, data: bytes) -> None:
        if not self._lib.pq_send(self._q, data, len(data)):
            raise QueueClosed("queue closed")

    def get(self, timeout: Optional[float] = None) -> bytes:
        size = ctypes.c_size_t()
        status = ctypes.c_int()
        ms = -1 if timeout is None else int(timeout * 1000)
        buf = self._lib.pq_receive(self._q, ctypes.byref(size), ms,
                                   ctypes.byref(status))
        st = status.value
        if st == 1:
            try:
                return ctypes.string_at(buf, size.value)
            finally:
                self._lib.pq_free(buf)
        if st == 0:
            raise QueueClosed("queue closed and drained")
        if st == -1:
            raise TimeoutError("queue get timed out")
        raise QueueKilled("queue killed")

    def close(self) -> None:
        self._lib.pq_close(self._q)

    def kill(self) -> None:
        self._lib.pq_kill(self._q)

    def qsize(self) -> int:
        return self._lib.pq_size(self._q)

    def __del__(self):
        try:
            if getattr(self, "_q", None):
                self._lib.pq_destroy(self._q)
                self._q = None
        except Exception:
            pass
