"""Datasets (reference: python/paddle/fluid/dataloader/dataset.py)."""

from __future__ import annotations

import bisect
from typing import Iterable, List, Sequence

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        from ..core.tensor import Tensor
        self.tensors = [t if isinstance(t, Tensor) else Tensor(t) for t in tensors]
        n = len(self.tensors[0])
        assert all(len(t) == n for t in self.tensors)

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        n = len(self.datasets[0])
        assert all(len(d) == n for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            out.extend(sample if isinstance(sample, (tuple, list)) else [sample])
        return tuple(out)

    def __len__(self):
        return len(self.datasets[0])


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx = len(self) + idx
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        sample_idx = idx if ds_idx == 0 else idx - self.cumulative_sizes[ds_idx - 1]
        return self.datasets[ds_idx][sample_idx]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = sum(lengths)
    assert total == len(dataset)
    perm = np.random.permutation(total)
    out, offset = [], 0
    for ln in lengths:
        out.append(Subset(dataset, perm[offset:offset + ln].tolist()))
        offset += ln
    return out


RandomSplit = random_split
