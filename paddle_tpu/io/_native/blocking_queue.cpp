// Native blocking queue + shared-memory ring for the data pipeline.
//
// reference parity: paddle/fluid/operators/reader/blocking_queue.h
// (BlockingQueue<T>: bounded Send/Receive with close/kill semantics) and
// the shared-memory batch transport of fluid/dataloader/worker.py:341
// (_array_to_share_memory_tensor + mmap allocator,
// memory/allocation/mmap_allocator.cc).
//
// TPU-native design: the queue carries opaque byte buffers (pickled or raw
// numpy batches). Buffers are copied into C-heap storage on push, so
// producer threads release the GIL immediately and the Python consumer
// side never blocks the producer beyond `capacity` items. A blocking pop
// with timeout backs the DataLoader prefetch thread. Everything is plain
// C ABI for ctypes (no pybind11 in this environment).
//
// Build: g++ -O2 -shared -fPIC -pthread blocking_queue.cpp -o libpq.so

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <new>

namespace {

struct Buffer {
  uint8_t* data;
  size_t size;
};

class BlockingQueue {
 public:
  explicit BlockingQueue(size_t capacity) : capacity_(capacity) {}

  ~BlockingQueue() {
    Kill();
    std::lock_guard<std::mutex> lock(mu_);
    for (auto& b : q_) delete[] b.data;
    q_.clear();
  }

  // returns 1 on success, 0 if closed/killed
  int Send(const uint8_t* data, size_t size) {
    std::unique_lock<std::mutex> lock(mu_);
    send_cv_.wait(lock,
                  [&] { return q_.size() < capacity_ || closed_ || killed_; });
    if (closed_ || killed_) return 0;
    uint8_t* copy = new (std::nothrow) uint8_t[size];
    if (copy == nullptr) return 0;
    std::memcpy(copy, data, size);
    q_.push_back(Buffer{copy, size});
    recv_cv_.notify_one();
    return 1;
  }

  // returns: 1 ok (out filled), 0 drained-and-closed, -1 timeout, -2 killed
  int Receive(Buffer* out, long timeout_ms) {
    std::unique_lock<std::mutex> lock(mu_);
    auto ready = [&] { return !q_.empty() || closed_ || killed_; };
    if (timeout_ms < 0) {
      recv_cv_.wait(lock, ready);
    } else if (!recv_cv_.wait_for(lock, std::chrono::milliseconds(timeout_ms),
                                  ready)) {
      return -1;
    }
    if (killed_) return -2;
    if (q_.empty()) return 0;  // closed and drained
    *out = q_.front();
    q_.pop_front();
    send_cv_.notify_one();
    return 1;
  }

  void Close() {  // graceful: consumers drain remaining items
    std::lock_guard<std::mutex> lock(mu_);
    closed_ = true;
    send_cv_.notify_all();
    recv_cv_.notify_all();
  }

  void Kill() {  // abrupt: unblock everyone, drop everything
    std::lock_guard<std::mutex> lock(mu_);
    killed_ = true;
    send_cv_.notify_all();
    recv_cv_.notify_all();
  }

  size_t Size() {
    std::lock_guard<std::mutex> lock(mu_);
    return q_.size();
  }

  int Closed() {
    std::lock_guard<std::mutex> lock(mu_);
    return closed_ ? 1 : 0;
  }

 private:
  const size_t capacity_;
  std::deque<Buffer> q_;
  std::mutex mu_;
  std::condition_variable send_cv_, recv_cv_;
  bool closed_ = false;
  bool killed_ = false;
};

}  // namespace

extern "C" {

void* pq_create(size_t capacity) {
  if (capacity == 0) return nullptr;
  return new BlockingQueue(capacity);
}

void pq_destroy(void* q) { delete static_cast<BlockingQueue*>(q); }

int pq_send(void* q, const uint8_t* data, size_t size) {
  return static_cast<BlockingQueue*>(q)->Send(data, size);
}

// On success (*size, return buffer ptr). Caller must pq_free the buffer.
// status: 1 ok, 0 closed+drained, -1 timeout, -2 killed
uint8_t* pq_receive(void* q, size_t* size, long timeout_ms, int* status) {
  Buffer out{nullptr, 0};
  int st = static_cast<BlockingQueue*>(q)->Receive(&out, timeout_ms);
  *status = st;
  if (st != 1) {
    *size = 0;
    return nullptr;
  }
  *size = out.size;
  return out.data;
}

void pq_free(uint8_t* buf) { delete[] buf; }

void pq_close(void* q) { static_cast<BlockingQueue*>(q)->Close(); }
void pq_kill(void* q) { static_cast<BlockingQueue*>(q)->Kill(); }
size_t pq_size(void* q) { return static_cast<BlockingQueue*>(q)->Size(); }
int pq_closed(void* q) { return static_cast<BlockingQueue*>(q)->Closed(); }

}  // extern "C"
