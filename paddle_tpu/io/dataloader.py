"""DataLoader.

Reference: python/paddle/fluid/reader.py:146 (DataLoader),
dataloader/dataloader_iter.py (single/multiprocess iters),
operators/reader/buffered_reader.cc (device double-buffering).

TPU redesign: worker processes produce numpy batches over a
multiprocessing queue (shared-memory tensors in the reference become plain
numpy + pickle here — the device copy is the real cost and is overlapped);
the device prefetcher replaces BufferedReader with an async ``device_put``
double buffer (XLA transfers are async; we just keep N batches in flight).
"""

from __future__ import annotations

import itertools
import multiprocessing as mp
import queue as queue_mod
import threading
from typing import Callable, Optional

import numpy as np

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler


def default_collate_fn(batch):
    """Stack samples into batched numpy arrays (structure-preserving)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s.data) for s in batch])
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, np.float32)
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn(list(items)) for items in zip(*batch))
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return np.asarray(batch)


class WorkerInfo:
    """reference: dataloader/worker.py WorkerInfo / paddle.io.get_worker_info:
    identifies the current DataLoader worker inside dataset code (e.g. to
    shard an IterableDataset across workers)."""

    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = None


def get_worker_info():
    """The WorkerInfo of the calling worker process, or None in the main
    process (reference: paddle.io.get_worker_info)."""
    return _worker_info


def _worker_loop(dataset, index_queue, out_queue, collate_fn, worker_init_fn,
                 worker_id, num_workers=0):
    """reference: dataloader/worker.py:257 _worker_loop."""
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset)
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    while True:
        item = index_queue.get()
        if item is None:
            break
        batch_id, indices = item
        try:
            samples = [dataset[i] for i in indices]
            out_queue.put((batch_id, collate_fn(samples), None))
        except Exception as e:  # propagate like ExceptionHolder
            out_queue.put((batch_id, None, e))


def _worker_loop_pipe(dataset, index_queue, conn, collate_fn, worker_init_fn,
                      worker_id, num_workers=0):
    """Worker for the native-queue transport: batches leave as RAW pickled
    frames over a dedicated pipe, so the consumer side deserializes exactly
    once (reference: worker.py:341 shared-memory handoff — here the bytes
    land in the C++ blocking queue instead of an mmap segment)."""
    import pickle
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset)
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    while True:
        item = index_queue.get()
        if item is None:
            break
        batch_id, indices = item
        try:
            samples = [dataset[i] for i in indices]
            payload = (batch_id, collate_fn(samples), None)
        except Exception as e:
            payload = (batch_id, None, e)
        try:
            conn.send_bytes(pickle.dumps(payload, protocol=4))
        except (BrokenPipeError, OSError):
            break
    conn.close()


def _drain_pipes(native_q, conns, stop_event):
    """Forward raw pickled frames from worker pipes into the C++ queue.

    Runs on a daemon thread holding NO reference to the iterator (weakref
    lifecycle stays with the consumer); always closes the native queue on
    exit so a blocked consumer raises instead of hanging.
    """
    from multiprocessing.connection import wait as conn_wait
    try:
        live = list(conns)
        while live and not stop_event.is_set():
            for conn in conn_wait(live, timeout=0.2):
                try:
                    frame = conn.recv_bytes()
                except (EOFError, OSError):
                    live.remove(conn)
                    continue
                native_q.put(frame)          # bounded: blocks in C
    except Exception:
        pass
    finally:
        native_q.close()


class _MultiprocessIter:
    def __init__(self, loader):
        self.loader = loader
        ctx = mp.get_context("fork")
        self.index_queue = ctx.Queue()
        self.out_queue = None
        self.workers = []
        self._native_q = None
        self._drain_thread = None
        self._stop_event = threading.Event()
        self._worker_conns = []

        # Native C++ blocking-queue transport (the reference's
        # reader-thread -> LoDTensorBlockingQueue stage,
        # reader/blocking_queue.h): workers pickle ONCE into a pipe, the
        # drain thread forwards raw bytes into bounded C-heap storage, the
        # consumer unpickles once. Falls back to an mp.Queue.
        if loader.use_shared_memory:
            try:
                from .native_queue import NativeBlockingQueue
                self._native_q = NativeBlockingQueue(
                    max(2, loader.prefetch_factor * loader.num_workers))
            except Exception:
                self._native_q = None
        if self._native_q is None:
            self.out_queue = ctx.Queue()

        for wid in range(loader.num_workers):
            if self._native_q is not None:
                r, w_conn = ctx.Pipe(duplex=False)
                self._worker_conns.append(r)
                target, sink = _worker_loop_pipe, w_conn
            else:
                target, sink = _worker_loop, self.out_queue
            w = ctx.Process(
                target=target,
                args=(loader.dataset, self.index_queue, sink,
                      loader.collate_fn, loader.worker_init_fn, wid,
                      loader.num_workers),
                daemon=True)
            w.start()
            self.workers.append(w)
            if self._native_q is not None:
                sink.close()                 # parent keeps the read end

        if self._native_q is not None:
            self._drain_thread = threading.Thread(
                target=_drain_pipes,
                args=(self._native_q, list(self._worker_conns),
                      self._stop_event),
                daemon=True)
            self._drain_thread.start()

        self.batch_iter = iter(loader.batch_sampler)
        self.send_id = 0
        self.recv_id = 0
        self.reorder = {}
        self.exhausted = False
        # prime the pipeline
        for _ in range(loader.num_workers * 2):
            self._send_next()

    def _recv(self):
        if self._native_q is not None:
            import pickle
            from .native_queue import QueueClosed, QueueKilled
            try:
                return pickle.loads(self._native_q.get())
            except (QueueClosed, QueueKilled):
                raise RuntimeError("DataLoader pipeline shut down")
        return self.out_queue.get()

    def _send_next(self):
        if self.exhausted:
            return
        try:
            indices = next(self.batch_iter)
        except StopIteration:
            self.exhausted = True
            return
        self.index_queue.put((self.send_id, indices))
        self.send_id += 1

    def __next__(self):
        if self.recv_id >= self.send_id and self.exhausted:
            self._shutdown()
            raise StopIteration
        while self.recv_id not in self.reorder:
            batch_id, data, err = self._recv()
            if err is not None:
                self._shutdown()
                raise err
            self.reorder[batch_id] = data
        data = self.reorder.pop(self.recv_id)
        self.recv_id += 1
        self._send_next()
        return data

    def _shutdown(self):
        self._stop_event.set()
        for _ in self.workers:
            try:
                self.index_queue.put(None)
            except Exception:
                pass
        for w in self.workers:
            w.join(timeout=1.0)
            if w.is_alive():
                w.terminate()
        self.workers = []
        if self._native_q is not None:
            self._native_q.kill()
        for c in self._worker_conns:
            try:
                c.close()
            except OSError:
                pass
        self._worker_conns = []

    def __del__(self):
        self._shutdown()


class _SingleProcessIter:
    def __init__(self, loader):
        self.loader = loader
        self.batch_iter = iter(loader.batch_sampler)

    def __next__(self):
        indices = next(self.batch_iter)
        samples = [self.loader.dataset[i] for i in indices]
        return self.loader.collate_fn(samples)


class _IterableDatasetIter:
    def __init__(self, loader):
        self.loader = loader
        self.it = iter(loader.dataset)

    def __next__(self):
        batch = list(itertools.islice(self.it, self.loader.batch_size))
        if not batch or (self.loader.drop_last and
                         len(batch) < self.loader.batch_size):
            raise StopIteration
        return self.loader.collate_fn(batch)


class _DevicePrefetcher:
    """Async device_put double-buffer (BufferedReader analogue)."""

    def __init__(self, inner, places, to_tensor, depth=2):
        self.inner = inner
        self.places = places
        self.to_tensor = to_tensor
        self.depth = depth
        self.buffer = []
        self._fill()

    def _convert(self, batch):
        import jax
        def conv(x):
            if isinstance(x, np.ndarray):
                arr = jax.device_put(x, self.places)
                return Tensor(arr) if self.to_tensor else arr
            if isinstance(x, (tuple, list)):
                return type(x)(conv(i) for i in x)
            if isinstance(x, dict):
                return {k: conv(v) for k, v in x.items()}
            return x
        return conv(batch)

    def _fill(self):
        while len(self.buffer) < self.depth:
            try:
                batch = next(self.inner)
            except StopIteration:
                break
            self.buffer.append(self._convert(batch))

    def __next__(self):
        # the consumer-facing wait: refill time IS the host-input-
        # pipeline time the training loop sits in — the goodput
        # ledger's data_wait bucket (one flag read when off)
        from ..monitor import goodput as _goodput
        with _goodput.measure("data_wait"):
            if not self.buffer:
                raise StopIteration
            out = self.buffer.pop(0)
            self._fill()
            return out

    def __iter__(self):
        return self


class DataLoader:
    """reference: fluid/reader.py DataLoader:146."""

    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.worker_init_fn = worker_init_fn
        self.use_buffer_reader = use_buffer_reader
        self.use_shared_memory = use_shared_memory
        self.prefetch_factor = prefetch_factor
        self.batch_size = batch_size
        self.drop_last = drop_last
        self._is_iterable_ds = isinstance(dataset, IterableDataset)

        if places is None:
            import jax
            places = jax.devices()[0]
        elif hasattr(places, "jax_device"):
            places = places.jax_device
        elif isinstance(places, (list, tuple)) and places:
            p0 = places[0]
            places = p0.jax_device if hasattr(p0, "jax_device") else p0
        self.places = places

        if not self._is_iterable_ds:
            if batch_sampler is not None:
                self.batch_sampler = batch_sampler
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)

    def __iter__(self):
        if self._is_iterable_ds:
            inner = _IterableDatasetIter(self)
        elif self.num_workers > 0:
            inner = _MultiprocessIter(self)
        else:
            inner = _SingleProcessIter(self)
        if self.use_buffer_reader:
            return _DevicePrefetcher(inner, self.places, self.return_list,
                                     depth=self.prefetch_factor)

        class _PlainIter:
            def __init__(self, it):
                self.it = it

            def __next__(self):
                from ..monitor import goodput as _goodput
                with _goodput.measure("data_wait"):
                    batch = next(self.it)
                    def conv(x):
                        if isinstance(x, np.ndarray):
                            return Tensor(x)
                        if isinstance(x, (tuple, list)):
                            return type(x)(conv(i) for i in x)
                        return x
                    return conv(batch)

            def __iter__(self):
                return self

        return _PlainIter(inner)

    def __len__(self):
        if self._is_iterable_ds:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)
