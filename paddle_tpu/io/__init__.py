from .dataset import (  # noqa: F401
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    Dataset,
    IterableDataset,
    RandomSplit,
    Subset,
    TensorDataset,
    random_split,
)
from .sampler import (  # noqa: F401
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    SubsetRandomSampler,
    WeightedRandomSampler,
)
from .dataloader import WorkerInfo, get_worker_info, DataLoader, default_collate_fn  # noqa: F401
