from . import dtype as dtypes
from .device import (
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    NPUPlace,
    Place,
    TPUPlace,
    device_count,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    set_device,
)
from .flags import get_flags, set_flags
from .random import Generator, default_generator, get_rng_state, make_rng, seed, set_rng_state
from .tensor import (
    Parameter,
    Tensor,
    apply,
    backward,
    enable_grad,
    is_grad_enabled,
    no_grad,
    set_grad_enabled,
)
