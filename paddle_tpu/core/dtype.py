"""Dtype registry.

Maps the reference's dtype enum (reference: paddle/fluid/framework/framework.proto:117-187)
onto native jax/numpy dtypes. bfloat16 is first-class on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype objects are numpy dtypes (jnp uses them natively).
bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_STR2DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "fp16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "fp32": float32,
    "float64": float64,
    "fp64": float64,
    "complex64": complex64,
    "complex128": complex128,
}


def convert_dtype(dtype):
    """Normalise str / np.dtype / jnp dtype to a canonical dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return _STR2DTYPE[dtype]
        except KeyError:
            raise ValueError(f"Unsupported dtype string: {dtype!r}")
    return np.dtype(dtype).type if not hasattr(dtype, "dtype") else dtype


def dtype_to_str(dtype) -> str:
    name = np.dtype(dtype).name
    return name


def is_floating_point(dtype) -> bool:
    return np.dtype(dtype).kind == "f" or dtype == bfloat16


def is_integer(dtype) -> bool:
    return np.dtype(dtype).kind in ("i", "u")


_DEFAULT_DTYPE = [float32]


def set_default_dtype(dtype):
    _DEFAULT_DTYPE[0] = convert_dtype(dtype)


def get_default_dtype():
    return _DEFAULT_DTYPE[0]
