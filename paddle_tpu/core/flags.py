"""Global configuration flags.

TPU-native analogue of the reference's three-tier flag system
(reference: paddle/fluid/platform/flags.cc — 48 gflags settable via env
``FLAGS_*`` and ``paddle.set_flags``; pybind/global_value_getter_setter.cc).

Here flags live in a single registry; values are read from the environment
(``FLAGS_<name>``) at first access and can be overridden with
:func:`set_flags` / read with :func:`get_flags`.
"""

from __future__ import annotations

import contextlib
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass
class _Flag:
    name: str
    default: Any
    help: str
    parser: Callable[[str], Any]
    value: Any = None
    explicitly_set: bool = False


def _parse_bool(s: str) -> bool:
    return s.lower() in ("1", "true", "yes", "on")


_REGISTRY: Dict[str, _Flag] = {}


def define_flag(name: str, default: Any, help: str = "") -> None:
    if isinstance(default, bool):
        parser: Callable[[str], Any] = _parse_bool
    elif isinstance(default, int):
        parser = int
    elif isinstance(default, float):
        parser = float
    else:
        parser = str
    _REGISTRY[name] = _Flag(name, default, help, parser)


def get_flag(name: str) -> Any:
    flag = _REGISTRY.get(name)
    if flag is None:
        raise KeyError(f"Unknown flag: {name!r}")
    if flag.explicitly_set:
        return flag.value
    env = os.environ.get(f"FLAGS_{name}")
    if env is not None:
        return flag.parser(env)
    return flag.default


def set_flags(flags: Dict[str, Any]) -> None:
    """paddle.set_flags analogue."""
    for name, value in flags.items():
        flag = _REGISTRY.get(name)
        if flag is None:
            raise KeyError(f"Unknown flag: {name!r}")
        flag.value = value
        flag.explicitly_set = True


def get_flags(names) -> Dict[str, Any]:
    if isinstance(names, str):
        names = [names]
    return {n: get_flag(n) for n in names}


@contextlib.contextmanager
def flag_scope(name: str, value: Any):
    """Temporarily override a flag for a with-block.

    Restores BOTH the previous value and the explicitly-set bit —
    ``set_flags`` alone cannot do that (it forces ``explicitly_set``,
    which would permanently shadow a ``FLAGS_*`` env override)."""
    flag = _REGISTRY.get(name)
    if flag is None:
        raise KeyError(f"Unknown flag: {name!r}")
    saved = (flag.value, flag.explicitly_set)
    flag.value = value
    flag.explicitly_set = True
    try:
        yield
    finally:
        flag.value, flag.explicitly_set = saved


# ---------------------------------------------------------------------------
# Core flag set (TPU-relevant subset of the reference's platform/flags.cc)
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False, "Scan op outputs for NaN/Inf after each eager op.")
define_flag("benchmark", False, "Synchronize after each op for benchmarking.")
define_flag("eager_jit_ops", True, "Cache-jit elementary eager ops.")
define_flag("default_dtype", "float32", "Default floating dtype.")
define_flag("allocator_strategy", "xla", "Kept for API parity; XLA owns HBM on TPU.")
define_flag("check_finite", False, "Check gradients finite after backward.")
define_flag("tpu_matmul_precision", "highest",
            "Precision for f32 dot ops (matmul/linear/einsum/attention). "
            "'highest' = full f32 (reference CUDA parity); 'default' lets the "
            "backend pick (bf16 passes on TPU). Convolutions follow the XLA "
            "backend default; use AMP/bf16 for the MXU fast path.")
define_flag("jit_channels_last", True,
            "Run 2-D NCHW conv/BN/pool chains channels-last (NHWC, the TPU "
            "MXU-native conv layout) inside jitted TrainStep traces: one "
            "transpose at model entry/exit instead of per-op NCHW dimension "
            "numbers. Public API layout is unchanged (docs/PARITY.md, "
            "internal-layout contract).")
define_flag("fused_conv_bn", True,
            "Fuse Conv2D+BatchNorm(+ReLU) chains in the vision models into "
            "one op (nn.functional.fused_conv_bn): conv epilogue fusion in "
            "XLA, one tape node in eager. f32 EMA buffers preserved under "
            "AMP.")
define_flag("log_level", "0", "Verbose log level (VLOG analogue).")
define_flag("scan_layers", True,
            "Run homogeneous transformer decoder/encoder stacks as ONE "
            "jax.lax.scan over layer-stacked parameters (nn.scan): trace+"
            "compile cost drops from O(num_layers) to O(1). Per-layer "
            "state_dict names and the LayerList API are unchanged "
            "(docs/PARITY.md internal-layout contract). Models opt in via "
            "their config (GPTConfig/BertConfig/ErnieConfig.scan_layers); "
            "this flag is the global kill switch.")
define_flag("scan_decode", True,
            "Run paged-KV-cache decode/prefill through the SAME "
            "scan-over-layers program layout as training (nn.scan."
            "scan_layers_with_cache): per-layer KV pages ride the scan as "
            "scanned-over state, so the decode program's trace+compile "
            "cost stays O(1) in depth. Off = the per-layer Python loop "
            "layout (same math, O(num_layers) trace; the kill switch if "
            "a backend mishandles scanned cache state). Legacy "
            "list-of-StaticCache decoding always uses the loop and "
            "records a scan_fallback_total counter.")
define_flag("chunked_ce_threshold", 4096,
            "Vocab size at or above which softmax cross-entropy streams "
            "over vocab chunks (nn.chunked_ce): online logsumexp with f32 "
            "accumulation, never materializing the full-vocab f32 logits/"
            "log-probs. 0 disables the chunked path.")
define_flag("chunked_ce_chunk", 8192,
            "Vocab chunk width for the streamed cross-entropy (rounded "
            "down to the vocab size; any remainder tail is masked, so "
            "non-multiple vocab sizes are exact).")
define_flag("monitor", False,
            "Stream hot-path telemetry into the paddle_tpu.monitor metrics "
            "registry: per-step TrainStep wall/dispatch timings, compile/"
            "recompile counters, grad-accum and LocalSGD sync boundaries. "
            "Off (default) = ZERO per-step registry writes on the train "
            "step hot path (tests pin this). Eager collective tracing is "
            "always on (registry writes are noise next to a shard_map "
            "dispatch) and the check_numerics watchdog is its own "
            "TrainStep argument — neither is gated by this flag.")
define_flag("memory_preflight", "",
            "OOM pre-flight check: when a TrainStep program compiles, "
            "compare its static HBM estimate (monitor.memory, from "
            "compiled.memory_analysis()) against the device HBM budget "
            "BEFORE step 1 touches real capacity. '' (default) = off; "
            "'warn' = RuntimeWarning when the estimate exceeds the "
            "budget; 'raise' = MemoryBudgetError. No-op when the budget "
            "is unknown (CPU test backend) and no explicit limit is set.")
define_flag("memory_preflight_limit_mb", 0,
            "Explicit HBM budget (MiB) for the pre-flight check; 0 = ask "
            "the device (memory_stats()['bytes_limit']). Set it to a "
            "TARGET chip's HBM to answer 'will this config fit a v5e?' "
            "from any dev machine.")
define_flag("flight_recorder", False,
            "Record every TrainStep into the crash flight recorder ring "
            "buffer even with FLAGS_monitor off, and install the "
            "unhandled-exception + faulthandler dump hooks at the first "
            "TrainStep construction. (FLAGS_monitor on also records "
            "steps; this flag adds the hooks and keeps recording when "
            "the registry stream is off.)")
define_flag("flight_recorder_dir", "",
            "Directory for flight-recorder dump files "
            "(flight_recorder_*.json); empty = current directory.")
define_flag("flight_recorder_capacity", 256,
            "Ring-buffer size of the flight recorder: how many recent "
            "step records survive to a crash dump.")
define_flag("checkpoint_verify", "manifest",
            "Checkpoint validation level for distributed.checkpoint "
            "restores and latest_step scans. 'manifest' (default) = a "
            "committed manifest must exist and every file it lists must "
            "be present with the recorded size (catches uncommitted and "
            "torn directories); 'full' = additionally re-checksum every "
            "file against the manifest CRCs (catches silent bit "
            "corruption, costs one read of the checkpoint); 'off' = "
            "existence check only (restores legacy pre-manifest "
            "checkpoints). CRCs are RECORDED at commit time only under "
            "'full' (they cost a full re-read of the staged tree); "
            "manifests without CRCs still verify at 'manifest' level.")
define_flag("collective_timeout_s", 0.0,
            "Watchdog timeout (seconds) for EAGER collectives in "
            "distributed.collective: a dispatch that does not return "
            "within the budget raises CollectiveTimeoutError (with a "
            "collective_timeout flight-recorder event) instead of "
            "hanging the controller forever. 0 (default) = no watchdog, "
            "direct dispatch. The budget covers the whole dispatch "
            "including a first-call trace+compile — set it well above "
            "the cold-start time.")
define_flag("chaos", "",
            "Deterministic fault-injection spec for "
            "paddle_tpu.testing.chaos (tests and bench.py --chaos): "
            "comma-separated 'site[@N|:prob][*times]' entries, e.g. "
            "'ckpt.write.torn@2,collective.hang:0.1'. Empty (default) = "
            "no injection, zero probe overhead.")
define_flag("chaos_seed", 0,
            "Seed for probability-based chaos sites: the same "
            "(seed, site, occurrence) triple always makes the same "
            "fire/no-fire decision, so chaos runs replay exactly.")
define_flag("serve_watchdog_s", 0.0,
            "Wall-clock watchdog (seconds) for serving prefill/decode "
            "dispatches (paddle_tpu.serving.engine): a dispatch that "
            "does not return within the budget raises "
            "DecodeWatchdogError (with a decode_watchdog flight event "
            "and dump) instead of stalling the serving loop forever. "
            "0 (default) = no watchdog, direct dispatch — zero "
            "overhead. The budget covers a whole dispatch including a "
            "cold compile; warmup() first, or set it well above "
            "cold-start time. Modeled on FLAGS_collective_timeout_s.")
define_flag("serve_prefix_cache", False,
            "Radix-tree prefix cache over the serving KV page pools "
            "(paddle_tpu.serving.prefix_cache): completed/evicted "
            "requests donate their full pages into a token-keyed radix "
            "tree with per-page refcounts; admission walks the tree and "
            "maps shared pages copy-on-write into the new slot's block "
            "table, so chat traffic with shared system prompts skips "
            "the redundant prefix prefill (vLLM PagedAttention / SGLang "
            "RadixAttention). Off (default) = the pre-cache path, "
            "bit-compatible — every admission prefills from token 0. "
            "Read at ServingEngine construction.")
define_flag("serve_prefill_chunk", 0,
            "Chunked prefill (paddle_tpu.serving.engine): > 0 = long "
            "prompts prefill in chunks of at most this many tokens, one "
            "chunk per engine iteration interleaved with the decode "
            "dispatches, so a long admission no longer stalls running "
            "decodes for its whole prompt (the TTFT-spike killer under "
            "bursty load). Later chunks attend over the pages earlier "
            "chunks wrote (the context-prefill program). 0 (default) = "
            "one-shot prefill, bit-compatible with the pre-chunking "
            "path. Read at ServingEngine construction.")
define_flag("serve_spec_k", 0,
            "Speculative decoding draft length (paddle_tpu.serving."
            "spec_decode): > 0 = an n-gram/prompt-lookup drafter (no "
            "second model) proposes up to k tokens per slot and ONE "
            "batched verify dispatch scores all k+1 positions against "
            "the paged cache; the accepted prefix plus one bonus token "
            "commit, rejected tails roll back by block-table truncation. "
            "Greedy output is token-identical to the non-speculative "
            "path (pinned); sampled slots run stochastic residual "
            "accept/reject (ISSUE 16), distribution-identical to plain "
            "sampled decode. 0 (default) = one decode dispatch per "
            "token, bit-compatible. Read at ServingEngine construction.")
define_flag("serve_spec_ngram", 3,
            "Longest suffix n-gram the speculative drafter matches "
            "against the request's own prompt+generated history "
            "(prompt-lookup decoding); it backs off to shorter n-grams "
            "down to 1 before giving up on a slot for the iteration.")
define_flag("pallas_ce", True,
            "Serve the streamed (chunked) hard-label cross-entropy with "
            "the fused Pallas kernel (ops.pallas.chunked_ce): online f32 "
            "logsumexp forward + one-pass dlogits backward, one VMEM-"
            "resident [rows, chunk] tile per grid step. Off = the pure-XLA "
            "fori_loop streaming path (nn.chunked_ce, bit-identical to "
            "the pre-kernel implementation). Soft labels and the dense "
            "mp-sharded path never use the kernel.")
define_flag("pallas_paged_decode", True,
            "Serve paged-KV decode attention (serving, S==1) with the "
            "Pallas flash-decode kernel (ops.pallas.paged_decode): K/V "
            "block-table pages are read in place via scalar-prefetch "
            "indexing — the [B, MB*bs, H, D] gathered context never "
            "materializes in HBM. Off = the XLA gather_pages + masked "
            "SDPA composition (bit-identical to the pre-kernel path).")
define_flag("pallas_int8", True,
            "Serve slim.QuantizedLinear matmuls with the Pallas int8 "
            "kernel (ops.pallas.quant_matmul): per-output-channel-scaled "
            "int8 x int8 -> int32 with a dequantize epilogue; weights "
            "stay int8 through the gemm (weight-only mode quantizes the "
            "activations dynamically per tensor). Off = the pre-kernel "
            "XLA paths (weight-only: dequantize-to-float matmul; static "
            "act_scale: XLA int8 dot).")
define_flag("pallas_bgmv", True,
            "Serve batched-LoRA shrink/expand projections (serving, "
            "multi-tenant decode) with the Pallas bgmv kernel "
            "(ops.pallas.bgmv): each slot's adapter id scalar-prefetch-"
            "indexes the stacked [n_adapters, r, d] A/B pools so the "
            "per-slot adapter weights are DMA'd straight from the pool "
            "— the gathered [B, r, d] copies never materialize in HBM. "
            "Off = the XLA gather + einsum composition (bit-identical "
            "to the pre-kernel math).")
define_flag("serve_kv_quant", "",
            "Quantized paged KV cache (paddle_tpu.serving.kv_cache): "
            "'int8' stores the K/V page pools as int8 with per-page, "
            "per-token-row, per-head absmax scales in a parallel f32 "
            "scale pool — roughly halving bytes per cached token vs "
            "bf16 (so ~2x slots per chip) at a documented greedy-decode "
            "parity bound. Quantization happens at write_pages; both "
            "the Pallas paged flash-decode kernel and the XLA gather "
            "fallback dequantize at read. Empty (default) = the "
            "bit-compatible full-precision pools (the flags-off "
            "oracle). Read once at engine/cache construction.")
define_flag("amp_int8_matmul", False,
            "EXPERIMENTAL: under an active amp.auto_cast region, run "
            "eligible nn.functional.linear matmuls through the Pallas "
            "int8 kernel with dynamic per-tensor activation/per-channel "
            "weight quantization and a straight-through dense backward "
            "(gradients flow to the UNquantized operands). Requires "
            "FLAGS_pallas_int8; off by default — int8 training is a "
            "numerics experiment, not the production AMP path.")
define_flag("pallas_interpret", False,
            "Run the ops.pallas kernel layer on non-TPU backends through "
            "the Pallas interpreter instead of falling back to XLA. "
            "SLOW — for kernel parity tests on CPU (the `pallas` pytest "
            "marker flips it); production CPU dispatch keeps the XLA "
            "fallbacks. flash_attention keeps its own shape gate in "
            "ops.attention and ignores this flag.")
define_flag("pipeline_schedule", "",
            "Global pipeline-schedule override for SPMD pipeline stacks: "
            "'1f1b' (one-forward-one-backward combined program) or "
            "'fill_drain' (GPipe fwd scan + autodiff mirror — the "
            "kill-switch-compatible fallback). Empty = resolve from the "
            "model/fleet strategy (pipeline_configs['schedule_mode']).")
define_flag("moe_dispatch", "sort",
            "MoE token dispatch/combine implementation "
            "(paddle_tpu.incubate.moe): 'sort' (default) = argsort-by-"
            "expert + static-shape gather/scatter — O(T·k·D) memory "
            "traffic, the TPU-efficient path; 'einsum' = the GShard "
            "one-hot dispatch/combine einsums that materialize "
            "O(T·E·C) tensors — the parity oracle and kill switch "
            "(bit-compatible with the pre-sort implementation). Both "
            "paths share one router, so capacity clipping and drop "
            "decisions are identical.")
define_flag("moe_expert_parallel", True,
            "Run stacked-expert MoE layers through the EXPLICIT "
            "expert-parallel program (shard_map manual over the 'ep' "
            "mesh axis + lax.all_to_all token exchange, double-buffered "
            "in capacity chunks so the all-to-alls overlap expert "
            "compute) when an ep>1 mesh is active and the backend can "
            "compile it. Off (or on incapable backends — XLA:CPU with "
            "another nontrivial mesh axis) = the GSPMD auto path: "
            "expert weights keep their P('ep', ...) specs and XLA "
            "inserts the collectives (counted moe_fallback_total "
            "telemetry, nn.scan fallback convention).")
define_flag("moe_a2a_chunks", 2,
            "Capacity-dim chunks of the expert-parallel all_to_all "
            "double buffer: each chunk's tokens-out all_to_all issues "
            "before any expert compute and its tokens-back all_to_all "
            "issues right after that chunk's FFN, so XLA's async "
            "scheduler can hide chunk i+1's exchange behind chunk i's "
            "compute (the PR 9 ppermute double-buffer recipe applied "
            "to ISSUE 10's expert exchange). 1 = no chunking.")
define_flag("recsys_dedup", True,
            "Unique/dedup embedding lookups in paddle_tpu.recsys "
            "(docs/RECSYS.md): sort-unique the batch ids, gather each "
            "distinct row ONCE, inverse-permute back — duplicate ids in "
            "a batch (the criteo hot-id regime) cost one row fetch, and "
            "sparse gradients accumulate over the unique set before the "
            "optimizer row update (the reference SparseTable push "
            "semantics). Off = the naive per-id gather/scatter — the "
            "parity oracle and kill switch (same math, O(batch) instead "
            "of O(unique) row traffic).")
define_flag("recsys_sharded_lookup", True,
            "Run ShardedEmbeddingTable lookups/updates through the "
            "EXPLICIT mesh program (shard_map manual over the 'ps' "
            "axis: each shard gathers the unique rows it owns, one "
            "psum assembles the batch — the PR 9/10 manual-collectives "
            "recipe) when a ps>1 mesh is active and the backend can "
            "compile it. Off (or on incapable backends — XLA:CPU with "
            "another nontrivial mesh axis) = the GSPMD auto path: the "
            "row-sharded table keeps its P('ps', ...) spec and XLA "
            "inserts the collectives (counted recsys_fallback_total "
            "telemetry, moe/nn.scan fallback convention).")
define_flag("trace", False,
            "Structured request/step tracing (monitor/trace.py): span "
            "trees with trace ids through the serving request lifecycle "
            "and the training step. Off (default) = zero span "
            "allocations and zero trace registry writes — the same "
            "zero-overhead contract as FLAGS_monitor, pinned by test.")
define_flag("trace_sample", 0.01,
            "Head sampling rate for structured traces (fraction of "
            "traces retained at random). Tail-based sampling keeps any "
            "trace containing an expired/shed/failed/watchdog/chaos/"
            "nonfinite event REGARDLESS of this rate, so anomalies "
            "always ship a full span tree.")
define_flag("trace_ring", 64,
            "Capacity of the retained-trace ring (flight-recorder "
            "model: newest N traces survive to a dump/export).")
define_flag("monitor_port", 0,
            "TCP port for the embedded admin/telemetry HTTP server "
            "(paddle_tpu.monitor.server): GET /metrics (Prometheus "
            "text with exemplars), /healthz, /readyz (503 while the "
            "serving engine is draining/shedding/watchdog-tripped), "
            "/statusz (fingerprint, flags, program table, occupancy, "
            "rates, SLO burn), /debug/flight, /debug/trace "
            "(?format=perfetto) and /debug/profile?seconds=N (arms a "
            "live profiler window, returns the chrome trace). Started "
            "by the serving engine and (opt-in) TrainStep when set; "
            "-1 = an ephemeral OS-assigned port (tests). 0 (default) "
            "= OFF: no thread, no socket, no registry writes — the "
            "zero-overhead contract, pinned by test.")
define_flag("monitor_host", "127.0.0.1",
            "Bind address for the admin server. Loopback by default — "
            "the plane exposes flags, program tables and profiles, so "
            "exposing it beyond the host is an explicit operator "
            "decision (front it with real auth if you must).")
define_flag("fleet_monitor_port", 0,
            "TCP port for the fleet federator's admin plane "
            "(paddle_tpu.monitor.fleet): a scrape loop pulls every "
            "configured replica /metrics page plus the router's "
            "registry into ONE host-labelled fleet registry and serves "
            "its own /metrics, /statusz (per-replica table), /healthz "
            "and /readyz (quorum of replica readiness). -1 = ephemeral "
            "OS-assigned port. 0 (default) = OFF: no scrape thread, no "
            "socket, no registry series — the same zero-overhead "
            "contract as FLAGS_monitor_port, pinned by test.")
define_flag("fleet_monitor_targets", "",
            "Comma-separated scrape targets for the fleet federator, "
            "each 'name=http://host:port' (the /metrics path is "
            "implied; /readyz and /debug/* derive from the same base). "
            "Empty (default) = federate the local process registry "
            "under the single host label 'fleet' — the in-process "
            "fleet shape, where router and replicas share one "
            "registry.")
define_flag("fleet_monitor_interval_s", 1.0,
            "Fleet federator scrape period in seconds. 1 Hz default — "
            "windowed fleet rates resolve at scrape granularity, and "
            "each scrape costs one /metrics page per target (see the "
            "scrape-interval guidance in docs/OBSERVABILITY.md).")
define_flag("fleet_monitor_slo", 0.0,
            "Fleet availability SLO objective as a fraction (e.g. "
            "0.999). Computed over the FEDERATED serve_requests_total "
            "deltas (good=completed; bad=expired/failed/shed) via the "
            "PR 11 SLOTracker; burn gauges publish as "
            "slo_burn_rate{slo='fleet_availability'}. 0 (default) = "
            "no fleet SLO tracker.")
define_flag("fleet_monitor_incident_dir", "",
            "Directory for anomaly-triggered incident bundles: when a "
            "fleet SLO burn alert fires or a tail-retained anomaly "
            "trace lands, the federator captures the implicated "
            "replica's flight-recorder doc, the merged Perfetto trace, "
            "the fleet statusz snapshot and the federated metrics page "
            "into a timestamped incident_* subdir (rate-limited; "
            "bundle dirs are .gitignore'd). Empty (default) = no "
            "incident capture.")
define_flag("train_goodput", False,
            "Training goodput ledger (monitor/goodput.py): attribute "
            "every second of trainer wall-clock to one exclusive "
            "bucket (productive_dispatch / compile / data_wait / "
            "checkpoint_stall / nonfinite_rollback / restart_gap / "
            "host_other), persist the totals in the CheckpointManager "
            "sidecar across SIGTERM->resume, and publish "
            "train_goodput_pct + train_badput_seconds_total{bucket} "
            "under FLAGS_monitor. Off (default) = one flag read per "
            "seam, no ledger allocation, no registry series — the "
            "zero-overhead contract, pinned by tests/test_goodput.py.")
define_flag("train_health_every", 0,
            "Per-layer model-health telemetry cadence: N > 0 compiles "
            "f32 per-layer grad-norm / param-norm / update-ratio "
            "side-outputs INTO the train step program (no extra "
            "dispatch; scan-over-layers stacks keep their per-layer "
            "param names) and publishes train_layer_* gauges every N "
            "optimizer steps, with an EWMA spike detector that "
            "tail-marks the step trace (reason 'health_spike') and "
            "attaches the last vector to flight-recorder dumps. "
            "0 (default) = OFF: the step program is bit-identical and "
            "nothing is computed or published.")
define_flag("serve_hot_swap", False,
            "Zero-downtime model lifecycle (serving/engine.py, ISSUE "
            "20): arm ServingEngine.swap_weights — load + verify a "
            "candidate manifest checkpoint, stage the new param tree "
            "beside the live one and cut over atomically at the next "
            "iteration boundary, in-flight slots finishing on the "
            "weights they started on (per-slot generation epoch; "
            "drain-and-restore fallback when HBM headroom can't hold "
            "two trees). Off (default) = swap_weights raises, no epoch "
            "bookkeeping exists, dispatch traffic is byte-identical to "
            "pre-lifecycle engines (pinned). Read once at engine "
            "construction.")
define_flag("serve_traffic_split", False,
            "Shadow/A-B traffic splitting (serving/router.py, ISSUE "
            "20): arm FleetRouter.set_traffic_split — a TrafficSplit "
            "policy hash-splits a deterministic fraction of requests "
            "onto a candidate replica (A/B) and/or mirrors a fraction "
            "as shadow copies (responses discarded but fully "
            "measured), with per-arm request counters, latency "
            "histograms and greedy-divergence counters. Off (default) "
            "= set_traffic_split raises, zero per-request overhead and "
            "zero new registry series (pinned). Read once at router "
            "construction.")
define_flag("serve_lifecycle", False,
            "SLO-guarded promotion controller (serving/lifecycle.py, "
            "ISSUE 20): arm LifecycleController — stage a candidate "
            "manifest on one replica, bake it under a traffic split "
            "while an SLOTracker watches the candidate arm's "
            "availability burn / non-finite rate / greedy divergence, "
            "then either promote (rolling swap, never two replicas "
            "down at once) or auto-roll-back to the previous weights, "
            "emitting flight events and an incident bundle on "
            "rollback. Off (default) = the controller refuses to "
            "construct; nothing else changes. Read once at controller "
            "construction.")
define_flag("compilation_cache", True,
            "Persist compiled XLA executables to disk so warm starts skip "
            "the 20-40s first-compile (reference analogue: the CUDA "
            "kernel/program caches). Applied at package import.")
define_flag("compilation_cache_dir", "",
            "Directory for the persistent compilation cache; empty = "
            "~/.cache/paddle_tpu/xla_cache (or $XDG_CACHE_HOME).")


def apply_compilation_cache() -> Optional[str]:
    """Enable jax's persistent compilation cache per the flags above.
    Called once at package import; safe to call again after set_flags.
    Returns the cache dir (or None when disabled)."""
    if not get_flag("compilation_cache"):
        return None
    try:
        import jax
        # never clobber a cache the user already configured (env var or
        # jax.config) — only supply the default when none is set
        existing = (os.environ.get("JAX_COMPILATION_CACHE_DIR")
                    or jax.config.jax_compilation_cache_dir)
        cache_dir = get_flag("compilation_cache_dir")
        if existing and not cache_dir:
            return existing
        if not cache_dir:
            base = os.environ.get("XDG_CACHE_HOME",
                                  os.path.expanduser("~/.cache"))
            cache_dir = os.path.join(base, "paddle_tpu", "xla_cache")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        return None
    return cache_dir


def matmul_precision():
    """Resolve the tpu_matmul_precision flag to a jax `precision=` value."""
    v = get_flag("tpu_matmul_precision")
    if v in (None, "", "default"):
        return None
    return v
