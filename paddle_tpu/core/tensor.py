"""Tensor facade and eager autograd engine.

TPU-native redesign of the reference's imperative engine:

- ``Tensor`` replaces ``VarBase`` (reference: paddle/fluid/imperative/layer.h) —
  a thin facade over ``jax.Array`` carrying ``stop_gradient``, ``.grad`` and a
  tape node.
- Eager op execution replaces ``Tracer::TraceOp``
  (reference: paddle/fluid/imperative/tracer.cc:146): every differentiable op
  goes through :func:`apply`, which computes the primal and records a
  ``jax.vjp`` closure on the tape — the per-op grad-node construction the
  reference does with DygraphGradOpMaker (imperative/layer.cc:492) falls out
  of JAX's functional VJP for free.
- ``Tensor.backward()`` replaces ``BasicEngine``
  (reference: paddle/fluid/imperative/basic_engine.cc:39-636): dependency
  counting + topological queue + gradient accumulation.

Under ``jit`` tracing the same ops run on tracer arrays with the tape
disabled; gradients there come from functional transforms instead.
"""

from __future__ import annotations

import contextlib
import threading
import weakref
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes
from .device import Place, default_place
from .flags import get_flag

__all__ = [
    "Tensor",
    "apply",
    "no_grad",
    "enable_grad",
    "set_grad_enabled",
    "is_grad_enabled",
    "Parameter",
]

_tls = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_tls, "grad_enabled", True)


def set_grad_enabled(mode: bool):
    _tls.grad_enabled = bool(mode)


class _GradModeCtx:
    """Context manager / decorator toggling eager tape recording."""

    def __init__(self, mode: bool):
        self.mode = mode

    def __enter__(self):
        self.prev = is_grad_enabled()
        set_grad_enabled(self.mode)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self.prev)
        return False

    def __call__(self, fn=None):
        if fn is None:
            return self

        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with _GradModeCtx(self.mode):
                return fn(*args, **kwargs)

        return wrapper


def no_grad(fn=None):
    ctx = _GradModeCtx(False)
    return ctx(fn) if fn is not None else ctx


def enable_grad(fn=None):
    ctx = _GradModeCtx(True)
    return ctx(fn) if fn is not None else ctx


class TapeNode:
    """One recorded differentiable op (analogue of GradOpNode)."""

    __slots__ = (
        "vjp_fn",
        "inputs",
        "out_avals",
        "out_refs",
        "name",
        "__weakref__",
    )

    def __init__(self, vjp_fn, inputs, out_avals, name=""):
        self.vjp_fn = vjp_fn
        # Tensors (diff inputs only, positionally matching vjp cotangents).
        self.inputs: List["Tensor"] = inputs
        self.out_avals = out_avals  # list[ShapeDtypeStruct]
        self.out_refs: List[Optional[weakref.ref]] = [None] * len(out_avals)
        self.name = name

    def __repr__(self):
        return f"TapeNode({self.name}, n_in={len(self.inputs)}, n_out={len(self.out_avals)})"


def _is_floating(arr) -> bool:
    d = arr.dtype
    return jnp.issubdtype(d, jnp.floating) or jnp.issubdtype(d, jnp.complexfloating)


class Tensor:
    """Eager tensor over a jax.Array.

    ``stop_gradient`` defaults to True (reference semantics: plain tensors
    don't require grad; ``Parameter`` flips it).
    """

    __slots__ = ("_data", "stop_gradient", "grad", "_node", "_out_idx",
                 "name", "persistable", "_retain_grads", "_hooks", "_layout",
                 "__weakref__")

    def __init__(self, data, dtype=None, place=None, stop_gradient=True, name=None):
        if isinstance(data, Tensor):
            data = data._data
        dtype = dtypes.convert_dtype(dtype)
        if isinstance(data, (jax.Array,)) or _is_tracer(data):
            arr = data if dtype is None else data.astype(dtype)
        else:
            np_data = np.asarray(data)
            if dtype is None and np_data.dtype == np.float64:
                np_data = np_data.astype(np.float32)
            arr = jnp.asarray(np_data, dtype=dtype)
        if place is not None and not _is_tracer(arr):
            arr = jax.device_put(arr, place.jax_device if isinstance(place, Place) else place)
        self._data = arr
        self.stop_gradient = bool(stop_gradient)
        self.grad: Optional[Tensor] = None
        self._node: Optional[TapeNode] = None
        self._out_idx: int = 0
        self.name = name
        self.persistable = False
        self._retain_grads = False
        self._hooks: List[Callable] = []
        # internal-layout tag (nn.layout planner): "NHWC" marks a tensor
        # whose physical layout is channels-last while the logical API
        # contract stays NCHW; None for ordinary tensors
        self._layout: Optional[str] = None

    # -- basic properties ---------------------------------------------------
    @property
    def data(self):
        return self._data

    @property
    def shape(self) -> List[int]:
        return list(self._data.shape)

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self) -> int:
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self):
        try:
            dev = self._data.devices().pop()
            return Place(dev.platform, dev.id)
        except Exception:
            return default_place()

    @property
    def is_leaf(self) -> bool:
        return self._node is None

    def numel(self) -> int:
        return self.size

    def numpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def item(self):
        return self._data.item()

    def tolist(self):
        return np.asarray(self._data).tolist()

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __iter__(self):
        """Iterate over the leading axis (static length, so loops unroll
        under trace). Without this, python's sequence-protocol fallback
        never terminates: jnp clamps out-of-range integer indices instead
        of raising IndexError."""
        if self.ndim == 0:
            raise TypeError("iteration over a 0-d tensor")
        return (self[i] for i in range(self._data.shape[0]))

    def __repr__(self):
        grad_part = "" if self.stop_gradient else ", stop_gradient=False"
        return (
            f"Tensor(shape={self.shape}, dtype={dtypes.dtype_to_str(self.dtype)}"
            f"{grad_part},\n       {np.asarray(self._data)!r})"
        )

    def __bool__(self):
        return bool(self._data)

    def __int__(self):
        return int(self._data)

    def __float__(self):
        return float(self._data)

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return format(str(self), spec)

    def __hash__(self):
        return id(self)

    def __deepcopy__(self, memo):
        """Copy value + flags; tape state never survives a deepcopy."""
        cls = type(self)
        new = cls.__new__(cls)
        new._data = self._data
        new.stop_gradient = self.stop_gradient
        new.grad = None
        new._node = None
        new._out_idx = 0
        new.name = self.name
        new.persistable = self.persistable
        new._retain_grads = False
        new._hooks = []
        new._layout = getattr(self, "_layout", None)
        for slot in getattr(cls, "__slots__", ()):
            if slot in Tensor.__slots__ or slot == "__weakref__":
                continue
            if hasattr(self, slot):
                import copy as _copy
                setattr(new, slot, _copy.deepcopy(getattr(self, slot), memo))
        memo[id(self)] = new
        return new

    # -- conversions --------------------------------------------------------
    def astype(self, dtype) -> "Tensor":
        dtype = dtypes.convert_dtype(dtype)
        return apply(lambda x: x.astype(dtype), self, name="cast")

    cast = astype

    def clone(self) -> "Tensor":
        return apply(lambda x: x + 0, self, name="clone")

    def detach(self) -> "Tensor":
        t = Tensor(self._data, stop_gradient=True)
        t.name = self.name
        return t

    def cpu(self) -> "Tensor":
        return Tensor(jax.device_put(self._data, jax.devices("cpu")[0]),
                      stop_gradient=self.stop_gradient)

    def to(self, device=None, dtype=None) -> "Tensor":
        arr = self._data
        if dtype is not None:
            arr = arr.astype(dtypes.convert_dtype(dtype))
        if device is not None:
            from .device import _parse
            arr = jax.device_put(arr, _parse(device).jax_device)
        t = Tensor(arr, stop_gradient=self.stop_gradient)
        return t

    def pin_memory(self) -> "Tensor":  # host staging is implicit on TPU
        return self

    def contiguous(self) -> "Tensor":
        return self

    # -- autograd -----------------------------------------------------------
    def retain_grads(self):
        self._retain_grads = True
        return self

    def register_hook(self, hook: Callable):
        self._hooks.append(hook)

        class _Handle:
            def remove(_self):
                if hook in self._hooks:
                    self._hooks.remove(hook)

        return _Handle()

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self):
        self.grad = None

    def backward(self, grad_tensor: Optional["Tensor"] = None, retain_graph: bool = False):
        """Run reverse-mode autograd from this tensor over the eager tape."""
        backward(self, grad_tensor=grad_tensor, retain_graph=retain_graph)

    @property
    def gradient(self):
        return None if self.grad is None else self.grad.numpy()

    # -- indexing -----------------------------------------------------------
    def __getitem__(self, idx) -> "Tensor":
        idx = _unwrap_index(idx)
        return apply(lambda x: x[idx], self, name="getitem")

    def __setitem__(self, idx, value):
        idx = _unwrap_index(idx)
        if isinstance(value, Tensor):
            new = apply(lambda x, v: x.at[idx].set(v), self, value, name="setitem")
        else:
            new = apply(lambda x: x.at[idx].set(value), self, name="setitem")
        self._adopt(new)

    def _adopt(self, other: "Tensor"):
        """In-place update: take over another tensor's value and tape link."""
        self._data = other._data
        self._node = other._node
        self._out_idx = other._out_idx
        if self._node is not None:
            self._node.out_refs[self._out_idx] = weakref.ref(self)
        self.stop_gradient = other.stop_gradient
        self._layout = other._layout

    # NOTE: arithmetic dunders and the broad method surface are attached by
    # paddle_tpu.tensor (functional API) at import time to avoid circularity.


class Parameter(Tensor):
    """Trainable tensor (stop_gradient=False), with an optimizer trainable flag."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "is_distributed", "spec")

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False
        self.spec = None  # jax PartitionSpec for SPMD sharding

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._data
    if isinstance(idx, tuple):
        return tuple(i._data if isinstance(i, Tensor) else i for i in idx)
    return idx


# ---------------------------------------------------------------------------
# Op dispatch
# ---------------------------------------------------------------------------

_amp_target_hook: Optional[Callable] = None  # installed by paddle_tpu.amp
_op_profile_hook: Optional[Callable] = None  # installed by paddle_tpu.profiler
# installed by paddle_tpu.nn.layout: (pre, post) planner callbacks. pre may
# rewrite args (insert the one exit transpose in front of a layout-unaware
# op); post propagates the channels-last tag through layout-transparent ops.
_layout_pre_hook: Optional[Callable] = None
_layout_post_hook: Optional[Callable] = None


def set_layout_hooks(pre: Optional[Callable], post: Optional[Callable]):
    """Install the internal-layout planner callbacks (nn.layout). Both are
    no-ops unless a channels-last scope is active on the calling thread."""
    global _layout_pre_hook, _layout_post_hook
    _layout_pre_hook = pre
    _layout_post_hook = post

# Eager-op jit cache (FLAGS_eager_jit_ops, reference analogue: the op-cache
# the reference's dygraph tracer maintains per op+sig, imperative/
# tracer.cc:146). The tape path's jax.vjp re-TRACES the op every call —
# hundreds of µs of host work per op; caching a jitted forward plus a
# jitted remat-backward keyed by (op identity, shapes, dtypes) turns hot
# eager loops into dict lookup + dispatch. Only closure-free fns are
# cacheable (a closure's captured values are invisible to the key); the
# cache holds a strong ref to fn so id() cannot be reused while cached,
# and is LRU-bounded.
import collections as _collections

_EAGER_FN_CACHE: "_collections.OrderedDict" = _collections.OrderedDict()
_EAGER_FN_CACHE_MAX = 1024
# dispatch-cache observability (tests + the eager bench): counts since
# interpreter start; reset freely from diagnostics
_EAGER_CACHE_STATS = {"hits": 0, "misses": 0}


def eager_cache_stats() -> dict:
    """Snapshot of the eager-op jit-cache hit/miss counters (process
    lifetime, monotonic). TrainStep.stats() and the monitor registry diff
    two snapshots to report a window's hit rate."""
    return dict(_EAGER_CACHE_STATS)


def _eager_cacheable(fn, static_kw) -> bool:
    if getattr(fn, "__closure__", None) is not None:
        return False
    # inline lambdas / local defs get a FRESH id() per call site execution:
    # caching them is all misses + LRU churn; only stable module-level
    # callables qualify
    if "<locals>" in getattr(fn, "__qualname__", ""):
        return False
    if static_kw:
        try:
            hash(tuple(sorted(static_kw.items())))
        except TypeError:
            return False
    return True


def _eager_cache_get(key):
    ent = _EAGER_FN_CACHE.get(key)
    if ent is not None:
        try:
            _EAGER_FN_CACHE.move_to_end(key)
        except KeyError:
            pass           # concurrently evicted; ent is still usable
    return ent


def _eager_cache_put(key, ent):
    _EAGER_FN_CACHE[key] = ent
    if len(_EAGER_FN_CACHE) > _EAGER_FN_CACHE_MAX:
        _EAGER_FN_CACHE.popitem(last=False)


def set_amp_target_hook(fn):
    """Install the autocast policy resolver: fn(op_name) -> dtype str or
    None. Resolved ONCE per apply() so deferred traces replay the
    forward's policy instead of reading thread-local state later."""
    global _amp_target_hook
    _amp_target_hook = fn


def set_op_profile_hook(fn):
    """Install/remove (None) the eager per-op timing hook: called with
    (op_name, seconds) after every apply() — the dygraph analogue of the
    reference's RecordEvent-per-op in imperative/tracer.cc."""
    global _op_profile_hook
    _op_profile_hook = fn


# stack of active static-graph recorders (paddle_tpu.static program_guard)
_static_recorders: List[Any] = []


def push_static_recorder(rec):
    _static_recorders.append(rec)


def pop_static_recorder():
    return _static_recorders.pop()


def annotate_test_variant(test_fn):
    """Attach a test-mode twin to the op just recorded (call immediately
    after the ``apply`` that recorded it): ``Program.clone(for_test=True)``
    swaps the recorded train-mode fn for this one — the analogue of the
    reference's is_test attribute flip in clone-for-test
    (framework.py Program.clone). The twin takes the SAME inputs and may
    return fewer outputs (trailing train-only outputs feed only write
    events, which clone-for-test strips)."""
    if _static_recorders:
        _static_recorders[-1]._annotate_test_variant(test_fn)


def record_mutation(target, new_value):
    """In-place state write (BN/IN running stats, quant moving averages,
    spectral-norm power-iteration vectors): assign ``target._data`` and,
    when a static recorder is active, record the write as an event in the
    op stream so Executor replay carries the mutation forward (reference:
    framework/executor.cc:170 — the reference Executor runs stat-update
    ops like any other op; here writes are explicit replayable events).

    While recording, the live tensor is NOT mutated: the build pass runs
    on placeholder zeros (the reference's Program build does not execute at
    all), so letting it write through would pollute real state with
    placeholder statistics; state starts evolving at the first
    Executor.run, which writes final buffer values back."""
    if _static_recorders and isinstance(new_value, Tensor):
        _static_recorders[-1]._record_write(target, new_value)
        return
    target._data = new_value._data if isinstance(new_value, Tensor) \
        else new_value


def apply(fn: Callable, *args, name: str = "", _cache_token=None, **static_kw):
    """Execute ``fn`` over raw arrays; record a VJP tape node if needed;
    when a static-graph recorder is active (static.program_guard), also
    append the op to the recording program for feed/fetch replay.

    ``_cache_token`` opts a closure-built op into the eager jit cache: a
    hashable token that must encode EVERY closure-captured value affecting
    the op's semantics (stride/padding/axis/...); the cache key becomes
    (name, token, signatures) instead of the function identity, so the
    per-call-site fresh closures of nn.functional stop defeating the cache."""
    if _layout_pre_hook is not None:
        args = _layout_pre_hook(name, args)
    result = _apply_impl(fn, *args, name=name, _cache_token=_cache_token,
                         **static_kw)
    if _layout_post_hook is not None:
        _layout_post_hook(name, args, result)
    if _static_recorders:
        _static_recorders[-1]._record_op(fn, name, static_kw, args, result)
    return result


def _apply_impl(fn: Callable, *args, name: str = "", _cache_token=None,
                **static_kw):
    """Execute ``fn`` over raw arrays; record a VJP tape node if needed.

    ``args`` may mix Tensors and array-likes/scalars; only float Tensor args
    with ``stop_gradient=False`` are differentiated. ``static_kw`` are closed
    over (never differentiated).
    """
    raw = [a._data if isinstance(a, Tensor) else a for a in args]
    # the AMP cast must live INSIDE the differentiated function: applied to
    # the primals outside, jax.vjp would hand back cotangents in the CAST
    # dtype while the producing op's output carries the original dtype —
    # an eager-tape dtype mismatch across any black/white-listed boundary.
    # The policy is resolved HERE to a concrete target dtype: deferred
    # traces (the lazily-jitted cached backward) capture the VALUE, never
    # re-reading thread-local autocast state at trace time.
    _amp_target = (_amp_target_hook(name)
                   if _amp_target_hook is not None else None)

    def _amp(vals):
        if _amp_target is None:
            return vals
        td = jnp.dtype(_amp_target)
        return [v.astype(td)
                if hasattr(v, "dtype") and jnp.issubdtype(v.dtype,
                                                          jnp.floating)
                and v.dtype != td else v
                for v in vals]

    record = False
    if is_grad_enabled():
        for a in args:
            if isinstance(a, Tensor) and not a.stop_gradient and not _is_tracer(a._data):
                record = True
                break

    if not record:
        if _op_profile_hook is not None and not any(
                _is_tracer(a) for a in raw):
            import time as _time
            t0 = _time.perf_counter()
            cast = _amp(raw)
            out = fn(*cast, **static_kw) if static_kw else fn(*cast)
            _op_profile_hook(name or "unnamed", _time.perf_counter() - t0)
            return _wrap_outputs(out, node=None)
        cast = _amp(raw)
        out = fn(*cast, **static_kw) if static_kw else fn(*cast)
        return _wrap_outputs(out, node=None)

    diff_idx = [
        i
        for i, a in enumerate(args)
        if isinstance(a, Tensor) and not a.stop_gradient and _is_floating(a._data)
    ]
    diff_tensors = [args[i] for i in diff_idx]

    def fn_diff(*diff_vals):
        vals = list(raw)
        for i, v in zip(diff_idx, diff_vals):
            vals[i] = v
        vals = _amp(vals)
        return fn(*vals, **static_kw) if static_kw else fn(*vals)

    t0 = None
    if _op_profile_hook is not None:
        import time as _time
        t0 = _time.perf_counter()

    cached = None
    if get_flag("eager_jit_ops") \
            and (_cache_token is not None
                 or _eager_cacheable(fn, static_kw)) \
            and all(hasattr(a, "shape") for a in raw):
        # all-array args only: jitting would trace positional python
        # scalars that the fn may use structurally (axis/shape values)
        try:
            # the AMP policy is applied INSIDE the jitted fns (so the vjp
            # casts cotangents back to the caller dtypes); its outcome must
            # therefore be part of the cache key — an op traced under one
            # autocast policy cannot serve another
            amp_token = _amp_target
            # token-keyed ops: nn.functional builds a FRESH closure per
            # call, so fn identity would never repeat — the caller-supplied
            # token (encoding every captured config value) replaces it in
            # the key, and the first call's closures serve all later calls
            # with the same (name, token, signature, amp) tuple
            key = (_cache_token if _cache_token is not None else id(fn),
                   name, tuple(diff_idx),
                   tuple((a.shape, str(a.dtype)) for a in raw),
                   amp_token,
                   tuple(sorted(static_kw.items())) if static_kw else ())
            hash(key)
        except TypeError:
            key = None
        cached = _eager_cache_get(key) if key is not None else None
        if key is not None:
            _EAGER_CACHE_STATS["hits" if cached is not None
                               else "misses"] += 1
        if cached is None and key is not None:
            def fwd_fn(vals):
                vals = _amp(vals)
                return fn(*vals, **static_kw) if static_kw else fn(*vals)

            def bwd_fn(vals, cots):
                def f(*dv):
                    vs = list(vals)
                    for i, v in zip(diff_idx, dv):
                        vs[i] = v
                    vs = _amp(vs)
                    return fn(*vs, **static_kw) if static_kw else fn(*vs)
                _, vjp = jax.vjp(f, *(vals[i] for i in diff_idx))
                return vjp(cots)

            cached = (fn, jax.jit(fwd_fn), jax.jit(bwd_fn))
            _eager_cache_put(key, cached)

    if cached is not None:
        # cached path: jitted forward now; backward (forward remat inside
        # one compiled call — cheap for elementary ops) deferred until the
        # tape actually needs it
        _, fwd_jit, bwd_jit = cached
        primals = fwd_jit(tuple(raw))
        captured_raw = tuple(raw)

        def vjp_fn(cots):
            return bwd_jit(captured_raw, cots)
    else:
        primals, vjp_fn = jax.vjp(fn_diff, *(raw[i] for i in diff_idx))

    if t0 is not None:
        import time as _time
        _op_profile_hook(name or "unnamed", _time.perf_counter() - t0)

    flat = primals if isinstance(primals, (tuple, list)) else (primals,)
    out_avals = [jax.ShapeDtypeStruct(p.shape, p.dtype) for p in flat]
    node = TapeNode(vjp_fn, diff_tensors, out_avals, name=name)
    result = _wrap_outputs(primals, node=node)

    if get_flag("check_nan_inf"):
        _check_nan_inf(result, name)
    return result


def _wrap_outputs(out, node: Optional[TapeNode]):
    multi = isinstance(out, (tuple, list))
    flat = list(out) if multi else [out]
    tensors = []
    for i, arr in enumerate(flat):
        sg = node is None or not _is_floating(arr)
        t = Tensor(arr, stop_gradient=sg)
        if node is not None:
            t._node = node
            t._out_idx = i
            node.out_refs[i] = weakref.ref(t)
        tensors.append(t)
    if multi:
        return tuple(tensors) if isinstance(out, tuple) else tensors
    return tensors[0]


def _check_nan_inf(result, name):
    flat = result if isinstance(result, (tuple, list)) else [result]
    for t in flat:
        if _is_floating(t._data):
            arr = np.asarray(t._data)
            if not np.isfinite(arr).all():
                raise FloatingPointError(
                    f"NaN/Inf detected in output of op {name or '<anonymous>'}"
                )


# ---------------------------------------------------------------------------
# Backward engine
# ---------------------------------------------------------------------------

def backward(root: Tensor, grad_tensor: Optional[Tensor] = None, retain_graph: bool = False):
    if root._node is None:
        if not root.stop_gradient:
            g = grad_tensor._data if grad_tensor is not None else jnp.ones_like(root._data)
            _accumulate_leaf(root, g)
        return

    if grad_tensor is None:
        if root.size != 1:
            raise RuntimeError(
                "backward() on a non-scalar tensor requires an explicit grad_tensor"
            )
        root_cot = jnp.ones_like(root._data)
    else:
        root_cot = grad_tensor._data

    # Phase 1: discover reachable graph and count consumers per node
    # (analogue of BasicEngine::PrepareDeps, basic_engine.cc:251).
    dep_count = {}
    visited = set()
    stack = [root._node]
    visited.add(root._node)
    dep_count[root._node] = 0
    while stack:
        node = stack.pop()
        for t in node.inputs:
            prod = t._node
            if prod is None:
                continue
            dep_count[prod] = dep_count.get(prod, 0) + 1
            if prod not in visited:
                visited.add(prod)
                stack.append(prod)

    # Phase 2: queue-driven execution with cotangent accumulation
    # (analogue of BasicEngine::Execute, basic_engine.cc:379).
    pending: dict = {root._node: {root._out_idx: root_cot}}
    ready = [root._node]
    while ready:
        node = ready.pop()
        cots_map = pending.pop(node, {})
        cots = []
        for i, aval in enumerate(node.out_avals):
            c = cots_map.get(i)
            if c is None:
                c = jnp.zeros(aval.shape, aval.dtype)
            out_ref = node.out_refs[i]
            out_t = out_ref() if out_ref is not None else None
            if out_t is not None:
                for hook in out_t._hooks:
                    res = hook(Tensor(c))
                    if res is not None:
                        c = res._data if isinstance(res, Tensor) else res
                if out_t._retain_grads and out_t._node is not None:
                    _accumulate_leaf(out_t, c)
            cots.append(c)

        in_cots = node.vjp_fn(tuple(cots) if len(cots) > 1 else cots[0])
        if not retain_graph:
            node.vjp_fn = None  # free residuals

        for t, g in zip(node.inputs, in_cots):
            prod = t._node
            if prod is None:
                _accumulate_leaf(t, g)
            else:
                slot = pending.setdefault(prod, {})
                if t._out_idx in slot:
                    slot[t._out_idx] = slot[t._out_idx] + g
                else:
                    slot[t._out_idx] = g
                dep_count[prod] -= 1
                if dep_count[prod] == 0:
                    ready.append(prod)


def _accumulate_leaf(t: Tensor, g):
    if t.stop_gradient:
        return
    for hook in t._hooks:
        if t._node is None:  # leaf hooks fire on the final grad
            res = hook(Tensor(g))
            if res is not None:
                g = res._data if isinstance(res, Tensor) else res
    if t.grad is None:
        t.grad = Tensor(g)
    else:
        t.grad = Tensor(t.grad._data + g)
    t.grad.stop_gradient = True
