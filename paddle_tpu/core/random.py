"""RNG management.

Replaces the reference's per-device stateful Philox generators
(reference: paddle/fluid/framework/generator.h:99-126) with JAX key
semantics, while keeping the stateful ``paddle.seed()`` UX:

- Eager mode: a process-global :class:`Generator` hands out fresh subkeys.
- Traced (jit) mode: stateful key draws are illegal under tracing, so a
  context-scoped *trace key* is installed by the jit wrapper; draws fold an
  increasing counter into it — pure and reproducible.
- TP-safe parallel RNG (reference: fleet/meta_parallel/parallel_layers/random.py:32
  RNGStatesTracker) is built on the same mechanism: named states are extra
  fold constants.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax
import numpy as np


class Generator:
    """Stateful key source for eager mode."""

    def __init__(self, seed: int = 0):
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._key = jax.random.key(int(seed))
        self._count = 0
        return self

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        self._count += 1
        return jax.random.fold_in(self._key, self._count)

    def get_state(self):
        return (self._seed, self._count)

    def set_state(self, state):
        self._seed, self._count = state
        self._key = jax.random.key(self._seed)


_default_generator = Generator(0)
_tls = threading.local()


def seed(value: int) -> Generator:
    """paddle.seed analogue: reseed the global generator."""
    return _default_generator.manual_seed(value)


def default_generator() -> Generator:
    return _default_generator


@contextlib.contextmanager
def trace_rng(key):
    """Install a pure trace-scoped key; draws are counter-folded subkeys."""
    prev = getattr(_tls, "trace_key", None)
    prev_count = getattr(_tls, "trace_count", 0)
    _tls.trace_key = key
    _tls.trace_count = 0
    try:
        yield
    finally:
        _tls.trace_key = prev
        _tls.trace_count = prev_count


def in_trace_rng() -> bool:
    return getattr(_tls, "trace_key", None) is not None


def make_rng(name: Optional[str] = None):
    """Return a fresh PRNG key.

    ``name`` selects a named stream (used by TP-safe dropout: the
    'local_seed' stream differs per model-parallel rank, 'global_seed' is
    identical across ranks — mirroring the reference's RNGStatesTracker).
    """
    key = getattr(_tls, "trace_key", None)
    if key is not None:
        _tls.trace_count = getattr(_tls, "trace_count", 0) + 1
        key = jax.random.fold_in(key, _tls.trace_count)
    else:
        key = _default_generator.next_key()
    if name is None:
        name = getattr(_tls, "stream_name", None)  # active stream_scope
    if name is not None:
        key = jax.random.fold_in(key, _stream_id(name))
    return key


@contextlib.contextmanager
def stream_scope(name: Optional[str]):
    """Route unnamed make_rng draws to a named stream for this scope (used
    by the TP RNGStatesTracker so dropout inside model-parallel regions
    draws from the per-rank 'local_seed' stream)."""
    prev = getattr(_tls, "stream_name", None)
    _tls.stream_name = name
    try:
        yield
    finally:
        _tls.stream_name = prev


_STREAMS = {}


def _stream_id(name: str) -> int:
    if name not in _STREAMS:
        # Deterministic across processes/runs (python's str hash is salted
        # per-process; named streams like 'global_seed' must agree across
        # model-parallel ranks).
        import hashlib
        digest = hashlib.sha256(name.encode()).digest()
        _STREAMS[name] = (int.from_bytes(digest[:4], "little") & 0x7FFFFFFF) or 1
    return _STREAMS[name]


def register_rng_stream(name: str, offset: int):
    """Register a named RNG stream with an explicit fold offset.

    Used by model-parallel setup so the 'local' stream folds in the tp rank.
    """
    _STREAMS[name] = int(offset) & 0x7FFFFFFF


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state):
    _default_generator.set_state(state)
