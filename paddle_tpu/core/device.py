"""Device model.

TPU-native replacement for the reference's Place variants
(reference: paddle/fluid/platform/place.h:26-95) and DeviceContextPool
(device_context.h:818). On TPU, XLA owns streams/contexts; what remains is a
thin `Place` naming scheme over `jax.Device` plus a process-wide default.
"""

from __future__ import annotations

import functools
from typing import Optional, Union

import jax


class Place:
    """A device place: ``tpu:0``, ``cpu``, ``tpu`` (first chip)."""

    def __init__(self, kind: str, index: int = 0):
        self.kind = kind
        self.index = index

    def __repr__(self):
        return f"Place({self.kind}:{self.index})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.kind == other.kind
            and self.index == other.index
        )

    def __hash__(self):
        return hash((self.kind, self.index))

    @property
    def jax_device(self) -> jax.Device:
        devs = _devices_by_kind(self.kind)
        if self.index >= len(devs):
            raise RuntimeError(
                f"Place {self} out of range: only {len(devs)} {self.kind} device(s)."
            )
        return devs[self.index]


class CPUPlace(Place):
    def __init__(self):
        super().__init__("cpu", 0)


class TPUPlace(Place):
    def __init__(self, index: int = 0):
        super().__init__("tpu", index)


# Aliases for reference-API parity (CUDAPlace users map to the accelerator).
CUDAPlace = TPUPlace
CUDAPinnedPlace = CPUPlace     # pinned host memory: plain host arrays here
XPUPlace = TPUPlace
NPUPlace = TPUPlace            # other-accelerator users land on the TPU


@functools.lru_cache(maxsize=None)
def _devices_by_kind(kind: str):
    if kind == "cpu":
        return jax.devices("cpu")
    # "tpu" means the default accelerator backend (tpu chip; on test rigs the
    # backend may be cpu-only — fall back so code is portable).
    try:
        return jax.devices()
    except RuntimeError:
        return jax.devices("cpu")


_DEFAULT_DEVICE: list = []


def _parse(device: Union[str, Place]) -> Place:
    if isinstance(device, Place):
        return device
    if ":" in device:
        kind, idx = device.split(":")
        return Place(kind, int(idx))
    return Place(device, 0)


def set_device(device: Union[str, Place]) -> Place:
    place = _parse(device)
    _DEFAULT_DEVICE[:] = [place]
    return place


def get_device() -> str:
    place = default_place()
    return f"{place.kind}:{place.index}"


def default_place() -> Place:
    if _DEFAULT_DEVICE:
        return _DEFAULT_DEVICE[0]
    backend = jax.default_backend()
    kind = "tpu" if backend != "cpu" else "cpu"
    return Place(kind, 0)


def is_compiled_with_cuda() -> bool:  # parity shim
    return False


def is_compiled_with_tpu() -> bool:
    return jax.default_backend() not in ("cpu",)


def device_count() -> int:
    return len(jax.devices())
