"""Metrics (reference: python/paddle/metric/metrics.py)."""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return np.asarray(x.data) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1)
        top = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        correct = top == label_np[..., None]
        return correct

    def update(self, correct, *args):
        correct = _np(correct)
        n = correct.shape[0]
        res = []
        for i, k in enumerate(self.topk):
            c = correct[..., :k].any(axis=-1).sum()
            self.total[i] += float(c)
            self.count[i] += n
            res.append(float(c) / n)
        return res[0] if len(res) == 1 else res

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        y = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (y == 1)).sum())
        self.fp += int(((p == 1) & (y == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        y = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (y == 1)).sum())
        self.fn += int(((p == 0) & (y == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = _np(preds)
        y = _np(labels).reshape(-1)
        if p.ndim == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        bins = np.minimum((p * self.num_thresholds).astype(np.int64),
                          self.num_thresholds)
        for b, lab in zip(bins, y):
            if lab:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # trapezoid over thresholds descending
        pos_cum = np.cumsum(self._stat_pos[::-1])
        neg_cum = np.cumsum(self._stat_neg[::-1])
        tpr = pos_cum / tot_pos
        fpr = neg_cum / tot_neg
        return float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional accuracy (reference: python/paddle/metric/metrics.py accuracy)."""
    import jax.numpy as jnp
    from ..core.tensor import apply

    def _acc(p, y):
        topk_idx = jnp.argsort(-p, axis=-1)[..., :k]
        if y.ndim == p.ndim:
            y = y[..., 0]
        correct = (topk_idx == y[..., None]).any(axis=-1)
        return jnp.mean(correct.astype(jnp.float32))

    return apply(_acc, input, label, name="accuracy")
