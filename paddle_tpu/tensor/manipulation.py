"""Shape/layout manipulation ops (reference: python/paddle/tensor/manipulation.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply

__all__ = [
    "reshape", "flatten", "transpose", "squeeze", "unsqueeze", "concat",
    "stack", "unstack", "split", "chunk", "tile", "expand", "expand_as",
    "broadcast_to", "gather", "gather_nd", "scatter", "scatter_nd",
    "scatter_nd_add", "index_select", "index_sample", "take_along_axis",
    "put_along_axis", "roll", "flip", "rot90", "unique", "unique_consecutive",
    "unbind", "slice", "strided_slice", "crop", "pad", "shard_index",
    "repeat_interleave", "moveaxis", "as_complex", "as_real", "tensordot",
    "tolist", "cast",
]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _ints(v):
    if isinstance(v, Tensor):
        return tuple(int(i) for i in np.asarray(v.data).reshape(-1))
    if isinstance(v, (int, np.integer)):
        return (int(v),)

    def one(i):
        if isinstance(i, Tensor):
            return int(i.item())
        if isinstance(i, (int, np.integer)):
            return int(i)
        # symbolic dims (jax.export shape polynomials, used by the ONNX
        # dynamic-batch exporter) pass through uncoerced
        return i
    return tuple(one(i) for i in v)


def cast(x, dtype):
    return _t(x).astype(dtype)


def reshape(x, shape, name=None):
    shp = _ints(shape)
    return apply(lambda a: jnp.reshape(a, shp), _t(x), name="reshape",
                 _cache_token=("reshape", shp))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def _flat(a):
        nd = a.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return jnp.reshape(a, new_shape)
    return apply(_flat, _t(x), name="flatten",
                 _cache_token=("flatten", start_axis, stop_axis))


def transpose(x, perm, name=None):
    p = _ints(perm)
    return apply(lambda a: jnp.transpose(a, p), _t(x), name="transpose",
                 _cache_token=("transpose", p))


def moveaxis(x, source, destination, name=None):
    return apply(lambda a: jnp.moveaxis(a, source, destination), _t(x), name="moveaxis")


def squeeze(x, axis=None, name=None):
    def _sq(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = _ints(axis)
        axes = tuple(ax % a.ndim for ax in axes if a.shape[ax % a.ndim] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a
    return apply(_sq, _t(x), name="squeeze")


def unsqueeze(x, axis, name=None):
    axes = _ints(axis)
    def _usq(a):
        out = a
        for ax in sorted(axes):
            out = jnp.expand_dims(out, ax)
        return out
    return apply(_usq, _t(x), name="unsqueeze")


def concat(x, axis=0, name=None):
    tensors = [_t(i) for i in x]
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply(lambda *arrs: jnp.concatenate(arrs, axis=ax), *tensors, name="concat")


def stack(x, axis=0, name=None):
    tensors = [_t(i) for i in x]
    return apply(lambda *arrs: jnp.stack(arrs, axis=axis), *tensors, name="stack")


def unstack(x, axis=0, num=None, name=None):
    x = _t(x)
    n = num or x.shape[axis]
    outs = apply(lambda a: tuple(jnp.moveaxis(a, axis, 0)[i] for i in range(n)),
                 x, name="unstack")
    return list(outs)


def unbind(x, axis=0):
    return unstack(x, axis)


def split(x, num_or_sections, axis=0, name=None):
    x = _t(x)
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    dim = x.shape[ax]
    if isinstance(num_or_sections, int):
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in num_or_sections]
        n_neg = builtins_sum(1 for s in sizes if s < 0)
        if n_neg:
            rest = dim - builtins_sum(s for s in sizes if s >= 0)
            sizes = [rest if s < 0 else s for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1]).tolist()

    def _split(a):
        return tuple(jax.lax.slice_in_dim(a, o, o + s, axis=ax) for o, s in zip(offsets, sizes))

    return list(apply(_split, x, name="split"))


def builtins_sum(it, start=0):
    total = start
    for v in it:
        total = total + v
    return total


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def tile(x, repeat_times, name=None):
    reps = _ints(repeat_times)
    return apply(lambda a: jnp.tile(a, reps), _t(x), name="tile")


def expand(x, shape, name=None):
    shp = _ints(shape)
    def _exp(a):
        tgt = list(shp)
        # -1 means keep original dim
        off = len(tgt) - a.ndim
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = a.shape[i - off]
        return jnp.broadcast_to(a, tuple(tgt))
    return apply(_exp, _t(x), name="expand")


def expand_as(x, y, name=None):
    y_shape = tuple(_t(y).shape)
    return apply(lambda a: jnp.broadcast_to(a, y_shape), _t(x), name="expand_as")


def broadcast_to(x, shape, name=None):
    shp = _ints(shape)
    return apply(lambda a: jnp.broadcast_to(a, shp), _t(x), name="broadcast_to")


def gather(x, index, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply(lambda a, i: jnp.take(a, i.reshape(-1).astype(jnp.int32), axis=ax),
                 _t(x), _t(index), name="gather")


def gather_nd(x, index, name=None):
    def _gnd(a, idx):
        idx = idx.astype(jnp.int32)
        lead = idx.shape[:-1]
        k = idx.shape[-1]
        flat_idx = idx.reshape(-1, k)
        out = a[tuple(flat_idx[:, i] for i in range(k))]
        return out.reshape(lead + a.shape[k:])
    return apply(_gnd, _t(x), _t(index), name="gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    def _sc(a, i, u):
        i = i.reshape(-1).astype(jnp.int32)
        if overwrite:
            return a.at[i].set(u)
        base = a.at[i].set(jnp.zeros_like(u))
        return base.at[i].add(u)
    return apply(_sc, _t(x), _t(index), _t(updates), name="scatter")


def scatter_nd_add(x, index, updates, name=None):
    def _snd(a, idx, u):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        flat_idx = idx.reshape(-1, k)
        flat_u = u.reshape((-1,) + a.shape[k:])
        return a.at[tuple(flat_idx[:, i] for i in range(k))].add(flat_u)
    return apply(_snd, _t(x), _t(index), _t(updates), name="scatter_nd_add")


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros
    base = zeros(shape, dtype=_t(updates).dtype)
    return scatter_nd_add(base, index, updates)


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


def index_sample(x, index, name=None):
    return apply(lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=1),
                 _t(x), _t(index), name="index_sample")


def take_along_axis(arr, indices, axis, name=None):
    return apply(lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=axis),
                 _t(arr), _t(indices), name="take_along_axis")


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def _put(a, i, v):
        i = i.astype(jnp.int32)
        v = jnp.broadcast_to(v, i.shape).astype(a.dtype)
        # build full index tuple
        idx = []
        for d in range(a.ndim):
            if d == axis:
                idx.append(i)
            else:
                shape = [1] * a.ndim
                shape[d] = a.shape[d]
                idx.append(jnp.broadcast_to(jnp.arange(a.shape[d]).reshape(shape), i.shape))
        if reduce == "add":
            return a.at[tuple(idx)].add(v)
        if reduce == "multiply" or reduce == "mul":
            return a.at[tuple(idx)].multiply(v)
        return a.at[tuple(idx)].set(v)
    return apply(_put, _t(arr), _t(indices), _t(values), name="put_along_axis")


def roll(x, shifts, axis=None, name=None):
    return apply(lambda a: jnp.roll(a, shifts, axis=axis), _t(x), name="roll")


def flip(x, axis, name=None):
    axes = _ints(axis)
    return apply(lambda a: jnp.flip(a, axis=axes), _t(x), name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), _t(x), name="rot90")


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # Unique has data-dependent output shape: eager-only (host round-trip),
    # mirroring the reference's CPU/GPU sync in unique_op.
    arr = np.asarray(_t(x).data)
    res = np.unique(arr, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(res)
    return tuple(Tensor(r) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    arr = np.asarray(_t(x).data)
    if axis is None:
        arr = arr.reshape(-1)
    keep = np.ones(arr.shape[0] if axis is None else arr.shape[axis], dtype=bool)
    a = arr if axis is None else np.moveaxis(arr, axis, 0)
    for i in range(1, a.shape[0]):
        keep[i] = not np.array_equal(a[i], a[i - 1])
    out = a[keep]
    outs = [Tensor(out if axis is None else np.moveaxis(out, 0, axis))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor(inv.astype(np.int64)))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, a.shape[0]))
        outs.append(Tensor(counts.astype(np.int64)))
    return outs[0] if len(outs) == 1 else tuple(outs)


def slice(input, axes, starts, ends, name=None):
    axes = _ints(axes)
    starts = _ints(starts)
    ends = _ints(ends)
    def _slice(a):
        idx = [np.s_[:]] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            idx[ax] = np.s_[s:e]
        return a[tuple(idx)]
    return apply(_slice, _t(input), name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    axes = _ints(axes)
    starts = _ints(starts)
    ends = _ints(ends)
    strides = _ints(strides)
    def _ss(a):
        idx = [np.s_[:]] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = np.s_[s:e:st]
        return a[tuple(idx)]
    return apply(_ss, _t(x), name="strided_slice")


def crop(x, shape=None, offsets=None, name=None):
    shp = _ints(shape)
    offs = _ints(offsets) if offsets is not None else (0,) * len(shp)
    def _crop(a):
        idx = tuple(np.s_[o:o + s] for o, s in zip(offs, shp))
        return a[idx]
    return apply(_crop, _t(x), name="crop")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ..nn.functional import pad as _pad
    return _pad(x, pad, mode=mode, value=value, data_format=data_format)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    size = index_num // nshards
    def _si(i):
        shard = i // size
        return jnp.where(shard == shard_id, i % size, ignore_value)
    return apply(_si, _t(input), name="shard_index")


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        reps = np.asarray(repeats.data)
        def _ri(a):
            return jnp.repeat(a, reps, axis=axis, total_repeat_length=int(reps.sum()))
        return apply(_ri, _t(x), name="repeat_interleave")
    return apply(lambda a: jnp.repeat(a, repeats, axis=axis), _t(x), name="repeat_interleave")


def as_complex(x, name=None):
    return apply(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), _t(x), name="as_complex")


def as_real(x, name=None):
    return apply(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), _t(x), name="as_real")


def tensordot(x, y, axes=2, name=None):
    from ..core.flags import matmul_precision
    return apply(lambda a, b: jnp.tensordot(a, b, axes=axes,
                                            precision=matmul_precision()),
                 _t(x), _t(y), name="tensordot")


def tolist(x):
    return _t(x).tolist()
