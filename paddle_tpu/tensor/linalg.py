"""Linear algebra ops (reference: python/paddle/tensor/linalg.py, linalg.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply

__all__ = [
    "norm", "matmul", "dist", "cond", "cholesky", "cholesky_solve", "svd",
    "qr", "lu", "eig", "eigh", "eigvals", "eigvalsh", "matrix_rank",
    "matrix_power", "det", "slogdet", "inv", "inverse", "pinv", "solve",
    "triangular_solve", "lstsq", "multi_dot", "cross", "histogram", "bincount",
    "mv", "corrcoef", "cov",
]

from .math import matmul  # re-export


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def _norm(a):
        if axis is None:
            flat = a.reshape(-1)
            if p == "fro" or p == 2:
                return jnp.sqrt(jnp.sum(flat * flat))
            if p == 1:
                return jnp.sum(jnp.abs(flat))
            if p == np.inf or p == "inf":
                return jnp.max(jnp.abs(flat))
            if p == -np.inf:
                return jnp.min(jnp.abs(flat))
            return jnp.sum(jnp.abs(flat) ** p) ** (1.0 / p)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if p == "fro":
            return jnp.sqrt(jnp.sum(a * a, axis=ax, keepdims=keepdim))
        if p == np.inf or p == "inf":
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == -np.inf:
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)
    return apply(_norm, _t(x), name="norm")


def dist(x, y, p=2, name=None):
    return norm(_t(x) - _t(y), p=float(p) if p not in ("fro", "inf") else p)


def cond(x, p=None, name=None):
    p = p or 2
    def _cond(a):
        if p == 2:
            s = jnp.linalg.svd(a, compute_uv=False)
            return s[..., 0] / s[..., -1]
        return jnp.linalg.norm(a, ord=p, axis=(-2, -1)) * \
            jnp.linalg.norm(jnp.linalg.inv(a), ord=p, axis=(-2, -1))
    return apply(_cond, _t(x), name="cond")


def cholesky(x, upper=False, name=None):
    def _chol(a):
        l = jnp.linalg.cholesky(a)
        return jnp.swapaxes(l, -1, -2) if upper else l
    return apply(_chol, _t(x), name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    def _cs(b, chol):
        c = jnp.swapaxes(chol, -1, -2) if upper else chol
        z = jax.scipy.linalg.solve_triangular(c, b, lower=True)
        return jax.scipy.linalg.solve_triangular(jnp.swapaxes(c, -1, -2), z, lower=False)
    return apply(_cs, _t(x), _t(y), name="cholesky_solve")


def svd(x, full_matrices=False, name=None):
    return apply(lambda a: jnp.linalg.svd(a, full_matrices=full_matrices), _t(x), name="svd")


def qr(x, mode="reduced", name=None):
    return apply(lambda a: jnp.linalg.qr(a, mode=mode), _t(x), name="qr")


def lu(x, pivot=True, get_infos=False, name=None):
    def _lu(a):
        lu_mat, piv = jax.scipy.linalg.lu_factor(a)
        return lu_mat, piv.astype(jnp.int32)
    outs = apply(_lu, _t(x), name="lu")
    if get_infos:
        return outs[0], outs[1], Tensor(np.zeros((), np.int32))
    return outs


def eig(x, name=None):
    # General eig is CPU-only in XLA; host round-trip.
    arr = np.asarray(_t(x).data)
    w, v = np.linalg.eig(arr)
    return Tensor(w), Tensor(v)


def eigvals(x, name=None):
    arr = np.asarray(_t(x).data)
    return Tensor(np.linalg.eigvals(arr))


def eigh(x, UPLO="L", name=None):
    return apply(lambda a: jnp.linalg.eigh(a, UPLO=UPLO), _t(x), name="eigh")


def eigvalsh(x, UPLO="L", name=None):
    return apply(lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), _t(x), name="eigvalsh")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    def _mr(a):
        return jnp.linalg.matrix_rank(a, rtol=tol)
    return apply(_mr, _t(x), name="matrix_rank")


def matrix_power(x, n, name=None):
    return apply(lambda a: jnp.linalg.matrix_power(a, n), _t(x), name="matrix_power")


def det(x, name=None):
    return apply(jnp.linalg.det, _t(x), name="det")


def slogdet(x, name=None):
    def _sld(a):
        sign, logdet = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logdet])
    return apply(_sld, _t(x), name="slogdet")


def inv(x, name=None):
    return apply(jnp.linalg.inv, _t(x), name="inv")


inverse = inv


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply(lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian),
                 _t(x), name="pinv")


def solve(x, y, name=None):
    return apply(jnp.linalg.solve, _t(x), _t(y), name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def _ts(a, b):
        a2 = jnp.swapaxes(a, -1, -2) if transpose else a
        return jax.scipy.linalg.solve_triangular(
            a2, b, lower=not upper, unit_diagonal=unitriangular)
    return apply(_ts, _t(x), _t(y), name="triangular_solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    def _lstsq(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return apply(_lstsq, _t(x), _t(y), name="lstsq")


def multi_dot(x, name=None):
    from ..core.flags import matmul_precision
    tensors = [_t(i) for i in x]
    return apply(lambda *arrs: jnp.linalg.multi_dot(arrs, precision=matmul_precision()),
                 *tensors, name="multi_dot")


def cross(x, y, axis=9, name=None):
    def _cross(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis with dim 3
            ax = next(i for i, d in enumerate(a.shape) if d == 3)
        return jnp.cross(a, b, axis=ax)
    return apply(_cross, _t(x), _t(y), name="cross")


def histogram(input, bins=100, min=0, max=0, name=None):
    def _hist(a):
        lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
        h, _ = jnp.histogram(a, bins=bins, range=(lo, hi))
        return h.astype(jnp.int32)
    return apply(_hist, _t(input), name="histogram")


def bincount(x, weights=None, minlength=0, name=None):
    arr = np.asarray(_t(x).data)
    w = np.asarray(weights.data) if isinstance(weights, Tensor) else weights
    return Tensor(np.bincount(arr, weights=w, minlength=minlength))


def mv(x, vec, name=None):
    from ..core.flags import matmul_precision
    return apply(lambda a, v: jnp.matmul(a, v, precision=matmul_precision()),
                 _t(x), _t(vec), name="mv")


def corrcoef(x, rowvar=True, name=None):
    return apply(lambda a: jnp.corrcoef(a, rowvar=rowvar), _t(x), name="corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply(lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0),
                 _t(x), name="cov")


# reference re-exports these tensor ops through paddle.linalg too
from .math import bmm, dot, t  # noqa: E402,F401
