"""Functional tensor API + Tensor method attachment.

Mirrors the reference's pattern of binding the `paddle.tensor.*` functional
surface onto the Tensor class as methods
(reference: python/paddle/tensor/__init__.py + fluid monkey-patching in
python/paddle/fluid/dygraph/math_op_patch.py).
"""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor, apply

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .tail import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403

from .sequence import *  # noqa: F401,F403

from . import (creation, linalg, logic, manipulation, math, random, search,
               sequence, stat, tail)

# ---------------------------------------------------------------------------
# Method attachment
# ---------------------------------------------------------------------------

_METHOD_SOURCES = [math, manipulation, logic, search, linalg, stat, creation,
                   random, tail]

_SKIP = {
    "to_tensor", "zeros", "ones", "full", "arange", "linspace", "eye", "empty",
    "meshgrid", "rand", "randn", "randint", "randperm", "uniform", "normal",
    "standard_normal", "broadcast_shape", "is_tensor", "scatter_nd",
    # module utilities in tensor.tail that are NOT tensor methods
    "set_printoptions", "batch", "check_shape", "disable_signal_handler",
    "flops", "create_parameter", "edit_distance",
}


def _attach_methods():
    for mod in _METHOD_SOURCES:
        for name in getattr(mod, "__all__", []):
            if name in _SKIP or hasattr(Tensor, name):
                continue
            fn = getattr(mod, name)
            if callable(fn):
                setattr(Tensor, name, fn)


_attach_methods()

# Paddle aliases with trailing-underscore in-place-ish semantics
# (reshape_ comes from tensor.tail with REAL in-place rebinding)
Tensor.transpose = manipulation.transpose
Tensor.scale = math.scale
Tensor.uniform_ = random.uniform_
Tensor.normal_ = random.normal_
Tensor.exponential_ = random.exponential_


def _inplace(name, fn):
    def method(self, *args, **kwargs):
        out = fn(self, *args, **kwargs)
        self._adopt(out)
        return self
    method.__name__ = name
    setattr(Tensor, name, method)


for _n, _f in [
    ("add_", math.add), ("subtract_", math.subtract), ("multiply_", math.multiply),
    ("scale_", math.scale), ("clip_", math.clip), ("ceil_", math.ceil),
    ("floor_", math.floor), ("exp_", math.exp), ("sqrt_", math.sqrt),
    ("rsqrt_", math.rsqrt), ("reciprocal_", math.reciprocal), ("round_", math.round),
    ("abs_", math.abs), ("tanh_", math.tanh), ("square_", math.square),
    ("zero_", lambda self: creation.zeros_like(self)),
    ("fill_", lambda self, v: creation.full_like(self, v)),
]:
    _inplace(_n, _f)


# -- arithmetic dunders -----------------------------------------------------

def _rbin(fn):
    def method(self, other):
        return fn(Tensor(other) if not isinstance(other, Tensor) else other, self)
    return method


Tensor.__add__ = math.add
Tensor.__radd__ = math.add
Tensor.__sub__ = math.subtract
Tensor.__rsub__ = _rbin(math.subtract)
Tensor.__mul__ = math.multiply
Tensor.__rmul__ = math.multiply
Tensor.__truediv__ = math.divide
Tensor.__rtruediv__ = _rbin(math.divide)
Tensor.__floordiv__ = math.floor_divide
Tensor.__rfloordiv__ = _rbin(math.floor_divide)
Tensor.__mod__ = math.remainder
Tensor.__rmod__ = _rbin(math.remainder)
Tensor.__pow__ = math.pow
Tensor.__rpow__ = _rbin(math.pow)
Tensor.__matmul__ = math.matmul
Tensor.__rmatmul__ = _rbin(math.matmul)
Tensor.__neg__ = math.neg
Tensor.__abs__ = math.abs
Tensor.__eq__ = logic.equal
Tensor.__ne__ = logic.not_equal
Tensor.__lt__ = logic.less_than
Tensor.__le__ = logic.less_equal
Tensor.__gt__ = logic.greater_than
Tensor.__ge__ = logic.greater_equal
Tensor.__and__ = logic.logical_and
Tensor.__or__ = logic.logical_or
Tensor.__xor__ = logic.logical_xor
Tensor.__invert__ = logic.logical_not
