"""Random ops (reference: python/paddle/tensor/random.py).

Eager calls draw fresh subkeys from the global Generator; inside a jit trace
the context trace-key is used (see core/random.py) so traced steps stay pure.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtypes
from ..core.random import make_rng
from ..core.tensor import Tensor, apply

__all__ = [
    "rand", "randn", "randint", "randint_like", "randperm", "uniform",
    "normal", "standard_normal", "bernoulli", "multinomial", "poisson",
    "uniform_", "normal_", "exponential_",
]


def _dt(dtype):
    d = dtypes.convert_dtype(dtype)
    return d if d is not None else dtypes.get_default_dtype()


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape.data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, min=0.0, max=1.0)


def randn(shape, dtype=None, name=None):
    return standard_normal(shape, dtype)


def standard_normal(shape, dtype=None, name=None):
    key = make_rng()
    return Tensor(jax.random.normal(key, _shape(shape), _dt(dtype)))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean.data if isinstance(mean, Tensor) else mean
        s = std.data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        key = make_rng()
        return Tensor(jax.random.normal(key, shp) * s + m)
    key = make_rng()
    return Tensor(jax.random.normal(key, _shape(shape or [1])) * std + mean)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else make_rng()
    return Tensor(jax.random.uniform(key, _shape(shape), _dt(dtype),
                                     minval=min, maxval=max))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    key = make_rng()
    d = dtypes.convert_dtype(dtype)
    if d == jnp.int64 and not jax.config.read("jax_enable_x64"):
        d = jnp.int32
    return Tensor(jax.random.randint(key, _shape(shape), low, high, dtype=d))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    return randint(low, high, shape=tuple(x.shape), dtype=dtype or "int64")


def randperm(n, dtype="int64", name=None):
    key = make_rng()
    d = dtypes.convert_dtype(dtype)
    if d == jnp.int64 and not jax.config.read("jax_enable_x64"):
        d = jnp.int32
    return Tensor(jax.random.permutation(key, n).astype(d))


def bernoulli(x, name=None):
    key = make_rng()
    return apply(lambda a: jax.random.bernoulli(key, a).astype(a.dtype), x, name="bernoulli")


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = make_rng()
    def _mn(p):
        logits = jnp.log(jnp.maximum(p, 1e-30))
        if replacement:
            return jax.random.categorical(key, logits, axis=-1,
                                          shape=(num_samples,) + p.shape[:-1]).T \
                if p.ndim > 1 else jax.random.categorical(key, logits, shape=(num_samples,))
        # without replacement: Gumbel top-k trick
        g = jax.random.gumbel(key, p.shape)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx
    return apply(_mn, x, name="multinomial")


def poisson(x, name=None):
    key = make_rng()
    return apply(lambda lam: jax.random.poisson(key, lam).astype(lam.dtype), x, name="poisson")


# In-place variants mutate the tensor's value (paddle `tensor.uniform_()` UX).
def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else make_rng()
    x._data = jax.random.uniform(key, tuple(x.shape), x.dtype, minval=min, maxval=max)
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    key = make_rng()
    x._data = (jax.random.normal(key, tuple(x.shape), x.dtype) * std + mean).astype(x.dtype)
    return x


def exponential_(x, lam=1.0, name=None):
    key = make_rng()
    x._data = (jax.random.exponential(key, tuple(x.shape), x.dtype) / lam).astype(x.dtype)
    return x
