"""Math ops (reference surface: python/paddle/tensor/math.py, ops.py).

Every op is a jnp composition dispatched through core.tensor.apply so the
eager tape records its VJP. Under jit tracing the same code path runs on
tracer arrays (tape off) and XLA fuses the compositions.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.flags import matmul_precision as _matmul_precision
from ..core.tensor import Tensor, apply

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "remainder", "mod",
    "pow", "matmul", "mm", "bmm", "dot", "inner", "outer", "t", "transpose_",
    "scale", "abs", "neg", "exp", "expm1", "log", "log2", "log10", "log1p",
    "sqrt", "rsqrt", "square", "reciprocal", "sign", "floor", "ceil", "round",
    "trunc", "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh",
    "tanh", "asinh", "acosh", "atanh", "atan2", "erf", "erfinv", "lgamma",
    "digamma", "sum", "mean", "max", "min", "prod", "amax", "amin",
    "logsumexp", "cumsum", "cumprod", "clip", "maximum", "minimum", "fmax",
    "fmin", "add_n", "multiplex", "isnan", "isinf", "isfinite", "nan_to_num",
    "stanh", "kron", "trace", "all", "any", "broadcast_shape", "lerp",
    "rad2deg", "deg2rad", "gcd", "lcm", "diff", "angle", "frac",
    "count_nonzero", "nansum", "nanmean", "heaviside", "logit", "increment",
]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _binop(fn, x, y, name):
    # Promote python scalars without creating spurious tensors.
    if not isinstance(x, Tensor):
        x = Tensor(x)
    if isinstance(y, (int, float, bool, np.number)):
        return apply(lambda a: fn(a, y), x, name=name)
    y = _t(y)
    return apply(fn, x, y, name=name)


def add(x, y, name=None):
    return _binop(jnp.add, x, y, "add")


def subtract(x, y, name=None):
    return _binop(jnp.subtract, x, y, "subtract")


def multiply(x, y, name=None):
    return _binop(jnp.multiply, x, y, "multiply")


def divide(x, y, name=None):
    return _binop(jnp.true_divide, x, y, "divide")


def floor_divide(x, y, name=None):
    return _binop(jnp.floor_divide, x, y, "floor_divide")


def remainder(x, y, name=None):
    return _binop(jnp.remainder, x, y, "remainder")


mod = remainder


def pow(x, y, name=None):
    return _binop(jnp.power, x, y, "pow")


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def _mm(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b, precision=_matmul_precision())
    return apply(_mm, _t(x), _t(y), name="matmul")


mm = matmul


def bmm(x, y, name=None):
    return apply(lambda a, b: jnp.matmul(a, b, precision=_matmul_precision()),
                 _t(x), _t(y), name="bmm")


def dot(x, y, name=None):
    return apply(lambda a, b: jnp.sum(a * b, axis=-1), _t(x), _t(y), name="dot")


def inner(x, y, name=None):
    return apply(lambda a, b: jnp.inner(a, b, precision=_matmul_precision()),
                 _t(x), _t(y), name="inner")


def outer(x, y, name=None):
    return apply(lambda a, b: jnp.outer(a, b), _t(x), _t(y), name="outer")


def t(x, name=None):
    return apply(lambda a: a.T, _t(x), name="t")


def transpose_(x, perm, name=None):
    return apply(lambda a: jnp.transpose(a, perm), _t(x), name="transpose")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = scale.item() if isinstance(scale, Tensor) else scale
    def _scale(a):
        out = a * s + bias if bias_after_scale else (a + bias) * s
        return out
    out = apply(_scale, _t(x), name="scale")
    if act is not None:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def increment(x, value=1.0, name=None):
    new = apply(lambda a: a + value, x, name="increment")
    x._adopt(new)
    return x


def _unary(fn, name):
    def op(x, name=None):
        return apply(fn, _t(x), name=name or op.__name__)
    op.__name__ = name
    return op


abs = _unary(jnp.abs, "abs")
neg = _unary(jnp.negative, "neg")
exp = _unary(jnp.exp, "exp")
expm1 = _unary(jnp.expm1, "expm1")
log = _unary(jnp.log, "log")
log2 = _unary(jnp.log2, "log2")
log10 = _unary(jnp.log10, "log10")
log1p = _unary(jnp.log1p, "log1p")
sqrt = _unary(jnp.sqrt, "sqrt")
rsqrt = _unary(jax.lax.rsqrt, "rsqrt")
square = _unary(jnp.square, "square")
reciprocal = _unary(jnp.reciprocal, "reciprocal")
sign = _unary(jnp.sign, "sign")
floor = _unary(jnp.floor, "floor")
ceil = _unary(jnp.ceil, "ceil")
round = _unary(jnp.round, "round")
trunc = _unary(jnp.trunc, "trunc")
sin = _unary(jnp.sin, "sin")
cos = _unary(jnp.cos, "cos")
tan = _unary(jnp.tan, "tan")
asin = _unary(jnp.arcsin, "asin")
acos = _unary(jnp.arccos, "acos")
atan = _unary(jnp.arctan, "atan")
sinh = _unary(jnp.sinh, "sinh")
cosh = _unary(jnp.cosh, "cosh")
tanh = _unary(jnp.tanh, "tanh")
asinh = _unary(jnp.arcsinh, "asinh")
acosh = _unary(jnp.arccosh, "acosh")
atanh = _unary(jnp.arctanh, "atanh")
erf = _unary(jax.scipy.special.erf, "erf")
erfinv = _unary(jax.scipy.special.erfinv, "erfinv")
lgamma = _unary(jax.scipy.special.gammaln, "lgamma")
digamma = _unary(jax.scipy.special.digamma, "digamma")
isnan = _unary(jnp.isnan, "isnan")
isinf = _unary(jnp.isinf, "isinf")
isfinite = _unary(jnp.isfinite, "isfinite")
angle = _unary(jnp.angle, "angle")


def frac(x, name=None):
    return apply(lambda a: a - jnp.trunc(a), _t(x), name="frac")


def atan2(x, y, name=None):
    return _binop(jnp.arctan2, x, y, "atan2")


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply(lambda a: scale_b * jnp.tanh(scale_a * a), _t(x), name="stanh")


def logit(x, eps=None, name=None):
    def _logit(a):
        if eps is not None:
            a = jnp.clip(a, eps, 1.0 - eps)
        return jnp.log(a / (1.0 - a))
    return apply(_logit, _t(x), name="logit")


def heaviside(x, y, name=None):
    return _binop(jnp.heaviside, x, y, "heaviside")


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    from ..core import dtypes
    d = dtypes.convert_dtype(dtype)
    return apply(lambda a: jnp.sum(a, axis=_axis(axis), dtype=d, keepdims=keepdim),
                 _t(x), name="sum")


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    from ..core import dtypes
    d = dtypes.convert_dtype(dtype)
    return apply(lambda a: jnp.nansum(a, axis=_axis(axis), dtype=d, keepdims=keepdim),
                 _t(x), name="nansum")


def mean(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.mean(a, axis=_axis(axis), keepdims=keepdim), _t(x), name="mean")


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.nanmean(a, axis=_axis(axis), keepdims=keepdim), _t(x), name="nanmean")


def max(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.max(a, axis=_axis(axis), keepdims=keepdim), _t(x), name="max")


def min(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.min(a, axis=_axis(axis), keepdims=keepdim), _t(x), name="min")


amax = max
amin = min


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    from ..core import dtypes
    d = dtypes.convert_dtype(dtype)
    return apply(lambda a: jnp.prod(a, axis=_axis(axis), dtype=d, keepdims=keepdim),
                 _t(x), name="prod")


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jax.scipy.special.logsumexp(a, axis=_axis(axis), keepdims=keepdim),
                 _t(x), name="logsumexp")


def cumsum(x, axis=None, dtype=None, name=None):
    from ..core import dtypes
    d = dtypes.convert_dtype(dtype)
    def _cs(a):
        if axis is None:
            return jnp.cumsum(a.reshape(-1), dtype=d)
        return jnp.cumsum(a, axis=int(axis), dtype=d)
    return apply(_cs, _t(x), name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    from ..core import dtypes
    d = dtypes.convert_dtype(dtype)
    return apply(lambda a: jnp.cumprod(a, axis=dim, dtype=d), _t(x), name="cumprod")


def clip(x, min=None, max=None, name=None):
    lo = min.item() if isinstance(min, Tensor) else min
    hi = max.item() if isinstance(max, Tensor) else max
    return apply(lambda a: jnp.clip(a, lo, hi), _t(x), name="clip")


def maximum(x, y, name=None):
    return _binop(jnp.maximum, x, y, "maximum")


def minimum(x, y, name=None):
    return _binop(jnp.minimum, x, y, "minimum")


def fmax(x, y, name=None):
    return _binop(jnp.fmax, x, y, "fmax")


def fmin(x, y, name=None):
    return _binop(jnp.fmin, x, y, "fmin")


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    tensors = [_t(i) for i in inputs]
    return apply(lambda *arrs: jnp.sum(jnp.stack(arrs), axis=0) if len(arrs) > 1 else arrs[0],
                 *tensors, name="add_n")


def multiplex(inputs, index, name=None):
    tensors = [_t(i) for i in inputs]
    idx = _t(index)
    def _mux(ix, *arrs):
        stacked = jnp.stack(arrs)  # [n, batch, ...]
        rows = ix.reshape(-1).astype(jnp.int32)
        batch = jnp.arange(stacked.shape[1])
        return stacked[rows, batch]
    return apply(_mux, idx, *tensors, name="multiplex")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf),
                 _t(x), name="nan_to_num")


def kron(x, y, name=None):
    return apply(jnp.kron, _t(x), _t(y), name="kron")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda a: jnp.trace(a, offset, axis1, axis2), _t(x), name="trace")


def all(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.all(a, axis=_axis(axis), keepdims=keepdim), _t(x), name="all")


def any(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.any(a, axis=_axis(axis), keepdims=keepdim), _t(x), name="any")


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.count_nonzero(a, axis=_axis(axis), keepdims=keepdim),
                 _t(x), name="count_nonzero")


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply(lambda a, b, w: a + w * (b - a), _t(x), _t(y), weight, name="lerp")
    return apply(lambda a, b: a + weight * (b - a), _t(x), _t(y), name="lerp")


def rad2deg(x, name=None):
    return apply(jnp.rad2deg, _t(x), name="rad2deg")


def deg2rad(x, name=None):
    return apply(jnp.deg2rad, _t(x), name="deg2rad")


def gcd(x, y, name=None):
    return _binop(jnp.gcd, x, y, "gcd")


def lcm(x, y, name=None):
    return _binop(jnp.lcm, x, y, "lcm")


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    pre = prepend.data if isinstance(prepend, Tensor) else prepend
    app = append.data if isinstance(append, Tensor) else append
    return apply(lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre, append=app),
                 _t(x), name="diff")
