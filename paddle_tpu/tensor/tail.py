"""Top-level API tail: small ops, inplace variants, and utility shims
closing the last gaps against the reference's `paddle.*` export list
(reference: python/paddle/__init__.py __all__; tensor/math.py addmm:1423,
tensor/manipulation.py broadcast_tensors, tensor/attribute.py rank/shape,
framework Tensor inplace methods reshape_/squeeze_/...).

Inplace variants on an immutable-array runtime: jax arrays cannot mutate,
so `x.op_()` computes functionally and REBINDS the tensor's buffer —
observable semantics (returns x, x changed) match the reference; aliasing
views of x do NOT see the change, which the reference forbids under
autograd anyway (inplace on leaf vars raises there).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply

__all__ = [
    "addmm", "broadcast_tensors", "conj", "diagonal", "floor_mod",
    "reverse", "rank", "shape", "reshape_", "scatter_", "squeeze_",
    "tanh_", "unsqueeze_", "create_parameter", "batch", "check_shape",
    "set_printoptions", "disable_signal_handler", "flops",
]


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """out = beta*input + alpha*(x @ y) (reference: tensor/math.py addmm)."""
    from ..core.flags import matmul_precision
    prec = matmul_precision()
    return apply(lambda i, a, b: beta * i
                 + alpha * jnp.matmul(a, b, precision=prec),
                 input, x, y, name="addmm")


def broadcast_tensors(inputs, name=None):
    """Broadcast a list of tensors to their common shape."""
    shapes = [tuple(t.shape) for t in inputs]
    target = np.broadcast_shapes(*shapes)
    return [apply(lambda a, s=target: jnp.broadcast_to(a, s), t,
                  name="broadcast_tensors") for t in inputs]


def conj(x, name=None):
    return apply(jnp.conj, x, name="conj")


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda a: jnp.diagonal(a, offset=offset, axis1=axis1,
                                        axis2=axis2),
                 x, name="diagonal")


def floor_mod(x, y, name=None):
    from .math import mod
    return mod(x, y)


def reverse(x, axis, name=None):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    return apply(lambda a: jnp.flip(a, axis=axes), x, name="reverse")


def rank(x, name=None):
    return apply(lambda a: jnp.asarray(a.ndim, jnp.int32), x, name="rank")


def shape(x, name=None):
    """Runtime shape as an int32 tensor (reference: fluid shape op)."""
    return apply(lambda a: jnp.asarray(a.shape, jnp.int32), x, name="shape")


# -- inplace variants -------------------------------------------------------


def _rebind(x: Tensor, new: Tensor) -> Tensor:
    x._data = new._data
    if hasattr(new, "_node") and new._node is not None:
        x._node = new._node
    return x


def reshape_(x, shape, name=None):
    from .manipulation import reshape
    return _rebind(x, reshape(x, shape))


def squeeze_(x, axis=None, name=None):
    from .manipulation import squeeze
    return _rebind(x, squeeze(x, axis))


def unsqueeze_(x, axis, name=None):
    from .manipulation import unsqueeze
    return _rebind(x, unsqueeze(x, axis))


def tanh_(x, name=None):
    return _rebind(x, apply(jnp.tanh, x, name="tanh_"))


def scatter_(x, index, updates, overwrite=True, name=None):
    from .manipulation import scatter
    return _rebind(x, scatter(x, index, updates, overwrite=overwrite))


# -- utility shims ----------------------------------------------------------


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Standalone parameter creation (reference: paddle.create_parameter /
    fluid/layers/tensor.py:77)."""
    from ..nn.layer import Layer

    holder = Layer()
    p = holder.create_parameter(tuple(shape), attr=attr, dtype=dtype,
                                is_bias=is_bias,
                                default_initializer=default_initializer)
    return p


def batch(reader, batch_size, drop_last=False):
    """Wrap a sample reader into a batch reader (reference:
    python/paddle/batch.py). Kept for legacy reader pipelines; new code
    should use paddle.io.DataLoader."""

    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader


def check_shape(shape):
    """Validate a shape argument (reference: paddle.check_shape)."""
    if isinstance(shape, Tensor):
        return
    for d in shape:
        if isinstance(d, int) and d < -1:
            raise ValueError(f"invalid dimension {d} in shape {shape}")


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Numpy-backed print options (reference: paddle.set_printoptions)."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def disable_signal_handler():
    """Parity no-op: the reference installs C++ fatal-signal hooks
    (paddle/fluid/platform/init.cc); the python/JAX runtime has none to
    disable."""


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Count model matmul/conv FLOPs for one forward (reference:
    hapi/dynamic_flops.py paddle.flops)."""
    import jax

    from ..core.tensor import no_grad
    from ..nn.layer import Layer

    if not isinstance(net, Layer):
        raise TypeError("paddle.flops expects a Layer")
    x = jnp.zeros(tuple(input_size), jnp.float32)

    from ..jit.functional import bind, buffer_arrays, param_arrays
    from ..core.random import trace_rng
    params = param_arrays(net)
    buffers = buffer_arrays(net)
    was_training = net.training
    net.eval()
    try:
        def fwd(p, xx):
            with bind(net, p, dict(buffers)), no_grad(), \
                    trace_rng(jax.random.key(0)):
                out = net(Tensor(xx))
            return out._data if isinstance(out, Tensor) else out

        analysis = jax.jit(fwd).lower(params, x).cost_analysis() or {}
        total = int(analysis.get("flops", 0))
    finally:
        if was_training:
            net.train()
    if print_detail:
        print(f"Total FLOPs: {total}")
    return total
