"""Top-level API tail: small ops, inplace variants, and utility shims
closing the last gaps against the reference's `paddle.*` export list
(reference: python/paddle/__init__.py __all__; tensor/math.py addmm:1423,
tensor/manipulation.py broadcast_tensors, tensor/attribute.py rank/shape,
framework Tensor inplace methods reshape_/squeeze_/...).

Inplace variants on an immutable-array runtime: jax arrays cannot mutate,
so `x.op_()` computes functionally and REBINDS the tensor's buffer —
observable semantics (returns x, x changed) match the reference; aliasing
views of x do NOT see the change, which the reference forbids under
autograd anyway (inplace on leaf vars raises there).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply

__all__ = [
    "addmm", "broadcast_tensors", "conj", "diagonal", "floor_mod",
    "reverse", "rank", "shape", "reshape_", "scatter_", "squeeze_",
    "tanh_", "unsqueeze_", "create_parameter", "batch", "check_shape",
    "set_printoptions", "disable_signal_handler", "flops",
    "diag_embed", "fill_diagonal_", "clip_by_norm", "edit_distance",
    "flatten_",
]


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """out = beta*input + alpha*(x @ y) (reference: tensor/math.py addmm)."""
    from ..core.flags import matmul_precision
    prec = matmul_precision()
    return apply(lambda i, a, b: beta * i
                 + alpha * jnp.matmul(a, b, precision=prec),
                 input, x, y, name="addmm")


def broadcast_tensors(inputs, name=None):
    """Broadcast a list of tensors to their common shape."""
    shapes = [tuple(t.shape) for t in inputs]
    target = np.broadcast_shapes(*shapes)
    return [apply(lambda a, s=target: jnp.broadcast_to(a, s), t,
                  name="broadcast_tensors") for t in inputs]


def conj(x, name=None):
    return apply(jnp.conj, x, name="conj")


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda a: jnp.diagonal(a, offset=offset, axis1=axis1,
                                        axis2=axis2),
                 x, name="diagonal")


def floor_mod(x, y, name=None):
    from .math import mod
    return mod(x, y)


def reverse(x, axis, name=None):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    return apply(lambda a: jnp.flip(a, axis=axes), x, name="reverse")


def rank(x, name=None):
    return apply(lambda a: jnp.asarray(a.ndim, jnp.int32), x, name="rank")


def shape(x, name=None):
    """Runtime shape as an int32 tensor (reference: fluid shape op)."""
    return apply(lambda a: jnp.asarray(a.shape, jnp.int32), x, name="shape")


# -- inplace variants -------------------------------------------------------


def _rebind(x: Tensor, new: Tensor) -> Tensor:
    x._adopt(new)        # value + tape link + out_ref bookkeeping
    return x


def reshape_(x, shape, name=None):
    from .manipulation import reshape
    return _rebind(x, reshape(x, shape))


def squeeze_(x, axis=None, name=None):
    from .manipulation import squeeze
    return _rebind(x, squeeze(x, axis))


def unsqueeze_(x, axis, name=None):
    from .manipulation import unsqueeze
    return _rebind(x, unsqueeze(x, axis))


def tanh_(x, name=None):
    return _rebind(x, apply(jnp.tanh, x, name="tanh_"))


def scatter_(x, index, updates, overwrite=True, name=None):
    from .manipulation import scatter
    return _rebind(x, scatter(x, index, updates, overwrite=overwrite))


# -- utility shims ----------------------------------------------------------


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Standalone parameter creation (reference: paddle.create_parameter /
    fluid/layers/tensor.py:77)."""
    from ..nn.layer import Layer

    holder = Layer()
    p = holder.create_parameter(tuple(shape), attr=attr, dtype=dtype,
                                is_bias=is_bias,
                                default_initializer=default_initializer)
    return p


def batch(reader, batch_size, drop_last=False):
    """Wrap a sample reader into a batch reader (reference:
    python/paddle/batch.py). Kept for legacy reader pipelines; new code
    should use paddle.io.DataLoader."""

    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader


def check_shape(shape):
    """Validate a shape argument (reference: paddle.check_shape)."""
    if isinstance(shape, Tensor):
        return
    for d in shape:
        if isinstance(d, int) and d < -1:
            raise ValueError(f"invalid dimension {d} in shape {shape}")


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Numpy-backed print options (reference: paddle.set_printoptions)."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def disable_signal_handler():
    """Parity no-op: the reference installs C++ fatal-signal hooks
    (paddle/fluid/platform/init.cc); the python/JAX runtime has none to
    disable."""


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Count model matmul/conv FLOPs for one forward (reference:
    hapi/dynamic_flops.py paddle.flops)."""
    import jax

    from ..core.tensor import no_grad
    from ..nn.layer import Layer

    if not isinstance(net, Layer):
        raise TypeError("paddle.flops expects a Layer")
    x = jnp.zeros(tuple(input_size), jnp.float32)

    from ..jit.functional import bind, buffer_arrays, param_arrays
    from ..core.random import trace_rng
    params = param_arrays(net)
    buffers = buffer_arrays(net)
    was_training = net.training
    net.eval()
    try:
        def fwd(p, xx):
            with bind(net, p, dict(buffers)), no_grad(), \
                    trace_rng(jax.random.key(0)):
                out = net(Tensor(xx))
            return out._data if isinstance(out, Tensor) else out

        analysis = jax.jit(fwd).lower(params, x).cost_analysis() or {}
        total = int(analysis.get("flops", 0))
    finally:
        if was_training:
            net.train()
    if print_detail:
        print(f"Total FLOPs: {total}")
    return total


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    """Batched diagonal-matrix construction (reference: tensor/creation.py
    diag_embed / operators/diag_embed_op.cc)."""

    def _de(a):
        n = a.shape[-1] + abs(offset)
        base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = base.at[..., r, c].set(a)
        d1 = dim1 % out.ndim
        d2 = dim2 % out.ndim
        perm = [i for i in range(out.ndim) if i not in (out.ndim - 2,
                                                        out.ndim - 1)]
        order = list(perm)
        for pos, axis in sorted([(d1, out.ndim - 2), (d2, out.ndim - 1)]):
            order.insert(pos, axis)
        return jnp.transpose(out, order)

    return apply(_de, input, name="diag_embed")


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    """Inplace diagonal fill (reference: tensor Tensor.fill_diagonal_ /
    operators/fill_diagonal_op.cc)."""

    def _fd(a):
        # true diagonal length for rectangular matrices with offset
        n = min(a.shape[-2] - max(-offset, 0), a.shape[-1] - max(offset, 0))
        idx = jnp.arange(max(n, 0))
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = a.at[..., r, c].set(value)
        if wrap and a.ndim == 2 and a.shape[0] > a.shape[1]:
            # numpy-style wrapped fill for tall matrices
            step = a.shape[1] + 1
            rows = jnp.arange(0, a.shape[0] * a.shape[1], step)
            flat = out.reshape(-1).at[rows].set(value)
            out = flat.reshape(a.shape)
        return out

    return _rebind(x, apply(_fd, x, name="fill_diagonal_"))


def clip_by_norm(x, max_norm, name=None):
    """Rescale x so ||x||_2 <= max_norm (reference:
    operators/clip_by_norm_op.h)."""

    def _cbn(a):
        norm = jnp.sqrt(jnp.sum(a.astype(jnp.float32) ** 2))
        scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm,
                                                                  1e-12),
                          1.0)
        return (a.astype(jnp.float32) * scale).astype(a.dtype)

    return apply(_cbn, x, name="clip_by_norm")


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Levenshtein distance between id sequences (reference:
    operators/edit_distance_op.cc — a host-side DP there too; the output
    feeds metrics, not gradients, so this runs on host numpy).

    Returns (distance [B, 1] float32, sequence_num [1] int64)."""
    hyp = np.asarray(input._data if isinstance(input, Tensor) else input)
    ref = np.asarray(label._data if isinstance(label, Tensor) else label)
    hl = (np.asarray(input_length._data if isinstance(input_length, Tensor)
                     else input_length).reshape(-1)
          if input_length is not None else None)
    rl = (np.asarray(label_length._data if isinstance(label_length, Tensor)
                     else label_length).reshape(-1)
          if label_length is not None else None)
    ignored = set(ignored_tokens or ())

    def seq(row, ln):
        s = row[:int(ln)] if ln is not None else row
        return [t for t in s.tolist() if t not in ignored]

    B = hyp.shape[0]
    out = np.zeros((B, 1), np.float32)
    for b in range(B):
        h = seq(hyp[b], hl[b] if hl is not None else None)
        r = seq(ref[b], rl[b] if rl is not None else None)
        m, n = len(h), len(r)
        dp = np.arange(n + 1, dtype=np.int64)
        for i in range(1, m + 1):
            prev = dp.copy()
            dp[0] = i
            for j in range(1, n + 1):
                dp[j] = min(prev[j] + 1, dp[j - 1] + 1,
                            prev[j - 1] + (h[i - 1] != r[j - 1]))
        d = float(dp[n])
        if normalized:
            d = d / max(n, 1)
        out[b, 0] = d
    return (Tensor(jnp.asarray(out)),
            Tensor(jnp.asarray(np.array([B], np.int64))))


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    from .manipulation import flatten
    return _rebind(x, flatten(x, start_axis, stop_axis))
