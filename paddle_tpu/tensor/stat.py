"""Statistics ops (reference: python/paddle/tensor/stat.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, apply

__all__ = ["mean", "std", "var", "median", "nanmedian", "quantile", "nanquantile"]

from .math import mean  # re-export


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _axis(axis):
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return axis


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda a: jnp.std(a, axis=_axis(axis), ddof=1 if unbiased else 0,
                                   keepdims=keepdim), _t(x), name="std")


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply(lambda a: jnp.var(a, axis=_axis(axis), ddof=1 if unbiased else 0,
                                   keepdims=keepdim), _t(x), name="var")


def median(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.median(a, axis=_axis(axis), keepdims=keepdim),
                 _t(x), name="median")


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.nanmedian(a, axis=_axis(axis), keepdims=keepdim),
                 _t(x), name="nanmedian")


def quantile(x, q, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.quantile(a, jnp.asarray(q), axis=_axis(axis),
                                        keepdims=keepdim), _t(x), name="quantile")


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.nanquantile(a, jnp.asarray(q), axis=_axis(axis),
                                           keepdims=keepdim), _t(x), name="nanquantile")
