"""Sequence operators over (padded, lengths) pairs.

reference parity: paddle/fluid/operators/sequence_ops/ —
sequence_pad_op, sequence_unpad_op, sequence_pool_op (SUM/AVERAGE/MAX/
SQRT/FIRST/LAST), sequence_reverse_op, sequence_softmax_op,
sequence_expand_as_op, sequence_enumerate_op, sequence_mask_op,
sequence_concat_op — all defined over LoD (ragged level-0) tensors.

TPU-native design: XLA requires static shapes, so ragged sequences are
carried as a PADDED batch [B, S, ...] plus an int lengths vector [B] —
exactly what sequence_pad produces from the reference's LoD input, and
what every production TPU text pipeline feeds. Each op consumes/produces
that pair; masking replaces LoD offset walks, so everything jits.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply

__all__ = ["sequence_pad", "sequence_unpad", "sequence_pool",
           "sequence_reverse", "sequence_softmax", "sequence_expand_as",
           "sequence_enumerate", "sequence_concat"]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _len_arr(lengths):
    return (lengths._data if isinstance(lengths, Tensor)
            else jnp.asarray(lengths)).astype(jnp.int32)


def sequence_pad(sequences, pad_value=0.0, maxlen: Optional[int] = None,
                 dtype=None):
    """List of ragged [L_i, ...] arrays -> (padded [B, S, ...],
    lengths [B]) (reference: sequence_pad_op — LoD in, padded out).
    Host-side by nature (ragged input cannot live on device)."""
    arrs = [np.asarray(s._data if isinstance(s, Tensor) else s)
            for s in sequences]
    lens = np.asarray([a.shape[0] for a in arrs], np.int32)
    S = int(maxlen if maxlen is not None else lens.max(initial=0))
    if lens.size and S < lens.max():
        raise ValueError(f"maxlen {S} < longest sequence {lens.max()}")
    tail = arrs[0].shape[1:] if arrs else ()
    out = np.full((len(arrs), S) + tail, pad_value,
                  dtype or (arrs[0].dtype if arrs else np.float32))
    for i, a in enumerate(arrs):
        out[i, :a.shape[0]] = a
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(lens))


def sequence_unpad(x, lengths) -> List[Tensor]:
    """(padded, lengths) -> list of ragged tensors (reference:
    sequence_unpad_op). Host-side: ragged output."""
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    lens = np.asarray(lengths._data if isinstance(lengths, Tensor)
                      else lengths)
    return [Tensor(jnp.asarray(arr[i, :int(l)]))
            for i, l in enumerate(lens)]


def sequence_pool(x, lengths, pool_type: str = "sum"):
    """Masked pool over the sequence dim (reference: sequence_pool_op —
    SUM/AVERAGE/MAX/SQRT/FIRST/LAST). x [B, S, ...], lengths [B] ->
    [B, ...]; empty sequences pool to 0."""
    x = _t(x)
    pool = pool_type.lower()
    if pool not in ("sum", "average", "mean", "max", "sqrt", "first",
                    "last"):
        raise ValueError(f"unknown pool_type {pool_type!r}")

    def impl(a, ln):
        S = a.shape[1]
        mask = (jnp.arange(S)[None, :] < ln[:, None])
        m = mask.reshape(mask.shape + (1,) * (a.ndim - 2))
        af = a.astype(jnp.float32) if pool in ("average", "mean", "sqrt") \
            else a
        if pool == "max":
            neg = jnp.finfo(a.dtype).min if jnp.issubdtype(
                a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
            out = jnp.where(m, a, neg).max(axis=1)
            return jnp.where((ln > 0).reshape((-1,) + (1,) * (a.ndim - 2)),
                             out, jnp.zeros_like(out))
        if pool == "first":
            return jnp.where(
                (ln > 0).reshape((-1,) + (1,) * (a.ndim - 2)),
                a[:, 0], jnp.zeros_like(a[:, 0]))
        if pool == "last":
            idx = jnp.maximum(ln - 1, 0)
            out = jnp.take_along_axis(
                a, idx.reshape((-1, 1) + (1,) * (a.ndim - 2)), axis=1
            )[:, 0]
            return jnp.where(
                (ln > 0).reshape((-1,) + (1,) * (a.ndim - 2)),
                out, jnp.zeros_like(out))
        total = jnp.where(m, af, 0).sum(axis=1)
        if pool == "sum":
            return total.astype(a.dtype)
        denom = jnp.maximum(ln, 1).astype(jnp.float32)
        denom = denom.reshape((-1,) + (1,) * (total.ndim - 1))
        if pool in ("average", "mean"):
            return (total / denom).astype(a.dtype)
        return (total / jnp.sqrt(denom)).astype(a.dtype)   # sqrt

    return apply(impl, x, Tensor(_len_arr(lengths)),
                 name=f"sequence_pool_{pool}")


def sequence_reverse(x, lengths):
    """Reverse each sequence in place, padding stays at the tail
    (reference: sequence_reverse_op)."""
    x = _t(x)

    def impl(a, ln):
        S = a.shape[1]
        pos = jnp.arange(S)[None, :]
        idx = jnp.where(pos < ln[:, None], ln[:, None] - 1 - pos, pos)
        return jnp.take_along_axis(
            a, idx.reshape(idx.shape + (1,) * (a.ndim - 2)), axis=1)

    return apply(impl, x, Tensor(_len_arr(lengths)),
                 name="sequence_reverse")


def sequence_softmax(x, lengths):
    """Per-sequence masked softmax (reference: sequence_softmax_op).
    x [B, S], padding positions get 0."""
    x = _t(x)

    def impl(a, ln):
        mask = jnp.arange(a.shape[1])[None, :] < ln[:, None]
        z = jnp.where(mask, a, -jnp.inf)
        out = jax.nn.softmax(z, axis=1)
        return jnp.where(mask, out, 0.0)

    return apply(impl, x, Tensor(_len_arr(lengths)),
                 name="sequence_softmax")


def sequence_expand_as(x, lengths):
    """Broadcast one row per sequence across its timesteps (reference:
    sequence_expand_as_op): x [B, ...] -> [B, S, ...] masked to
    lengths, with S = max length."""
    x = _t(x)
    # static-shape requirement: the padded width is resolved on host from
    # concrete lengths (XLA cannot size an output from traced values)
    ln = np.asarray(_len_arr(lengths))
    S = int(ln.max(initial=0))

    def impl2(a, ln_):
        rep = jnp.repeat(a[:, None], S, axis=1)
        mask = jnp.arange(S)[None, :] < ln_[:, None]
        m = mask.reshape(mask.shape + (1,) * (a.ndim - 1))
        return jnp.where(m, rep, jnp.zeros_like(rep))

    return apply(impl2, x, Tensor(_len_arr(lengths)),
                 name="sequence_expand_as")


def sequence_enumerate(x, lengths, win_size: int, pad_value: int = 0):
    """Sliding windows of ids per sequence (reference:
    sequence_enumerate_op): x [B, S] int -> [B, S, win_size]; positions
    past each sequence's end (and window overhang) take pad_value."""
    x = _t(x)

    def impl(a, ln):
        S = a.shape[1]
        pos = jnp.arange(S)[:, None] + jnp.arange(win_size)[None, :]
        gathered = jnp.take(a, jnp.clip(pos, 0, S - 1), axis=1)
        valid = (pos[None] < ln[:, None, None])
        return jnp.where(valid, gathered, pad_value)

    return apply(impl, x, Tensor(_len_arr(lengths)),
                 name="sequence_enumerate")


def sequence_concat(xs_and_lens: Sequence[Tuple]):
    """Concatenate corresponding sequences from multiple (padded,
    lengths) pairs (reference: sequence_concat_op). Host-side repack —
    output width is the sum of per-batch lengths."""
    parts = [(np.asarray(x._data if isinstance(x, Tensor) else x),
              np.asarray(l._data if isinstance(l, Tensor) else l))
             for x, l in xs_and_lens]
    B = parts[0][0].shape[0]
    out_lens = np.sum([l for _, l in parts], axis=0).astype(np.int32)
    S = int(out_lens.max(initial=0))
    tail = parts[0][0].shape[2:]
    out = np.zeros((B, S) + tail, parts[0][0].dtype)
    for b in range(B):
        o = 0
        for a, l in parts:
            n = int(l[b])
            out[b, o:o + n] = a[b, :n]
            o += n
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(out_lens))
