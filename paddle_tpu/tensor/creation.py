"""Tensor creation ops (reference surface: python/paddle/tensor/creation.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtypes
from ..core.random import make_rng
from ..core.tensor import Tensor, apply

__all__ = [
    "to_tensor", "zeros", "ones", "full", "zeros_like", "ones_like",
    "full_like", "arange", "linspace", "eye", "empty", "empty_like",
    "diag", "diagflat", "tril", "triu", "meshgrid", "assign", "clone",
    "numel", "complex", "real", "imag",
]


def _dt(dtype, default=None):
    d = dtypes.convert_dtype(dtype)
    if d is None:
        d = default if default is not None else dtypes.get_default_dtype()
    return d


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    return Tensor(data, dtype=dtypes.convert_dtype(dtype), place=place,
                  stop_gradient=stop_gradient)


def zeros(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None) -> Tensor:
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def zeros_like(x, dtype=None, name=None) -> Tensor:
    return apply(lambda a: jnp.zeros_like(a, dtype=dtypes.convert_dtype(dtype)), _sg(x), name="zeros_like")


def ones_like(x, dtype=None, name=None) -> Tensor:
    return apply(lambda a: jnp.ones_like(a, dtype=dtypes.convert_dtype(dtype)), _sg(x), name="ones_like")


def full_like(x, fill_value, dtype=None, name=None) -> Tensor:
    return apply(lambda a: jnp.full_like(a, fill_value, dtype=dtypes.convert_dtype(dtype)), _sg(x), name="full_like")


def empty(shape, dtype=None, name=None) -> Tensor:
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None) -> Tensor:
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None) -> Tensor:
    if end is None:
        start, end = 0, start
    for v in (start, end, step):
        if isinstance(v, float):
            dtype = dtype or "float32"
    d = dtypes.convert_dtype(dtype) if dtype else jnp.int64
    if d == jnp.int64 and not jax.config.read("jax_enable_x64"):
        d = jnp.int32
    start = start.item() if isinstance(start, Tensor) else start
    end = end.item() if isinstance(end, Tensor) else end
    step = step.item() if isinstance(step, Tensor) else step
    return Tensor(jnp.arange(start, end, step, dtype=d))


def linspace(start, stop, num, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.linspace(start, stop, int(num), dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None) -> Tensor:
    def _diag(a):
        out = jnp.diag(a, offset)
        if a.ndim == 1 and padding_value != 0:
            mask = jnp.eye(out.shape[0], k=offset, dtype=bool)
            out = jnp.where(mask, out, jnp.asarray(padding_value, out.dtype))
        return out
    return apply(_diag, x, name="diag")


def diagflat(x, offset=0, name=None) -> Tensor:
    return apply(lambda a: jnp.diagflat(a, offset), x, name="diagflat")


def tril(x, diagonal=0, name=None) -> Tensor:
    return apply(lambda a: jnp.tril(a, diagonal), x, name="tril")


def triu(x, diagonal=0, name=None) -> Tensor:
    return apply(lambda a: jnp.triu(a, diagonal), x, name="triu")


def meshgrid(*args, **kwargs):
    tensors = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    outs = jnp.meshgrid(*[t.data if isinstance(t, Tensor) else jnp.asarray(t) for t in tensors],
                        indexing="ij")
    return [Tensor(o) for o in outs]


def assign(x, output=None) -> Tensor:
    src = Tensor(x) if not isinstance(x, Tensor) else x
    out = apply(lambda a: a + 0 if jnp.issubdtype(a.dtype, jnp.number) else a, src, name="assign")
    if output is not None:
        output._adopt(out)
        return output
    return out


def clone(x, name=None) -> Tensor:
    return x.clone()


def numel(x, name=None) -> Tensor:
    return Tensor(np.int64(x.size))


def complex(real, imag, name=None) -> Tensor:
    return apply(lambda r, i: r + 1j * i, real, imag, name="complex")


def real(x, name=None) -> Tensor:
    return apply(jnp.real, x, name="real")


def imag(x, name=None) -> Tensor:
    return apply(jnp.imag, x, name="imag")


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def _sg(x):
    return x if isinstance(x, Tensor) else Tensor(x)
