"""Search / sort ops (reference: python/paddle/tensor/search.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply

__all__ = [
    "argmax", "argmin", "argsort", "sort", "topk", "kthvalue", "mode",
    "index_sample", "masked_select", "where", "nonzero", "searchsorted",
    "bucketize",
]

from .logic import masked_select, nonzero, where  # re-export
from .manipulation import index_sample  # re-export


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    return apply(lambda a: jnp.argmax(a, axis=axis, keepdims=keepdim).astype(jnp.int32),
                 _t(x), name="argmax")


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    return apply(lambda a: jnp.argmin(a, axis=axis, keepdims=keepdim).astype(jnp.int32),
                 _t(x), name="argmin")


def argsort(x, axis=-1, descending=False, name=None):
    return apply(lambda a: jnp.argsort(a, axis=axis, descending=descending),
                 _t(x), name="argsort")


def sort(x, axis=-1, descending=False, name=None):
    return apply(lambda a: jnp.sort(a, axis=axis, descending=descending),
                 _t(x), name="sort")


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())
    ax = -1 if axis is None else axis

    def _topk(a):
        src = jnp.moveaxis(a, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(src, k)
        else:
            vals, idx = jax.lax.top_k(-src, k)
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax)

    return apply(_topk, _t(x), name="topk")


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def _kth(a):
        s = jnp.sort(a, axis=axis)
        i = jnp.argsort(a, axis=axis)
        vals = jnp.take(s, k - 1, axis=axis)
        idx = jnp.take(i, k - 1, axis=axis)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx
    return apply(_kth, _t(x), name="kthvalue")


def mode(x, axis=-1, keepdim=False, name=None):
    def _mode(a):
        srt = jnp.sort(a, axis=axis)
        idx = jnp.argsort(a, axis=axis)
        same = jnp.concatenate(
            [jnp.ones_like(jnp.take(srt, jnp.array([0]), axis=axis), dtype=jnp.int32),
             (jnp.diff(srt, axis=axis) == 0).astype(jnp.int32)], axis=axis)
        # run lengths via cumulative trick
        runs = jnp.cumsum(same, axis=axis) * same
        pos = jnp.argmax(runs, axis=axis, keepdims=True)
        vals = jnp.take_along_axis(srt, pos, axis=axis)
        inds = jnp.take_along_axis(idx, pos, axis=axis)
        if not keepdim:
            vals = jnp.squeeze(vals, axis)
            inds = jnp.squeeze(inds, axis)
        return vals, inds
    return apply(_mode, _t(x), name="mode")


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    out_dtype = jnp.int32

    def _ss(seq, v):
        if seq.ndim == 1:
            out = jnp.searchsorted(seq, v, side=side)
        else:
            out = jax.vmap(lambda s, q: jnp.searchsorted(s, q, side=side))(
                seq.reshape(-1, seq.shape[-1]), v.reshape(-1, v.shape[-1])
            ).reshape(v.shape)
        return out.astype(out_dtype)

    return apply(_ss, _t(sorted_sequence), _t(values), name="searchsorted")


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)
