"""einsum.

reference parity: python/paddle/tensor/einsum.py:731 — supports explicit
('ij,jk->ik') and implicit ('ij,jk') forms, ellipsis broadcasting, traces
and reductions.

TPU-native: delegates to jnp.einsum (XLA contracts on the MXU with its own
contraction-order planner); the `apply` wrapper threads the eager tape and
the framework matmul-precision policy.
"""

from __future__ import annotations

from ..core.flags import matmul_precision
from ..core.tensor import Tensor, apply

__all__ = ["einsum"]


def einsum(equation: str, *operands):
    import jax.numpy as jnp

    ts = [o if isinstance(o, Tensor) else Tensor(jnp.asarray(o))
          for o in operands]
    prec = matmul_precision()

    def impl(*arrs):
        return jnp.einsum(equation, *arrs, precision=prec)

    return apply(impl, *ts, name="einsum")
