"""Comparison / logical / bitwise ops (reference: python/paddle/tensor/logic.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "equal_all", "allclose", "isclose", "logical_and",
    "logical_or", "logical_xor", "logical_not", "bitwise_and", "bitwise_or",
    "bitwise_xor", "bitwise_not", "is_empty", "is_tensor", "where",
    "masked_select", "nonzero",
]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


def _cmp(fn, x, y, name):
    if isinstance(y, (int, float, bool, np.number)):
        return apply(lambda a: fn(a, y), _t(x), name=name)
    return apply(fn, _t(x), _t(y), name=name)


def equal(x, y, name=None):
    return _cmp(jnp.equal, x, y, "equal")


def not_equal(x, y, name=None):
    return _cmp(jnp.not_equal, x, y, "not_equal")


def greater_than(x, y, name=None):
    return _cmp(jnp.greater, x, y, "greater_than")


def greater_equal(x, y, name=None):
    return _cmp(jnp.greater_equal, x, y, "greater_equal")


def less_than(x, y, name=None):
    return _cmp(jnp.less, x, y, "less_than")


def less_equal(x, y, name=None):
    return _cmp(jnp.less_equal, x, y, "less_equal")


def equal_all(x, y, name=None):
    return apply(lambda a, b: jnp.array_equal(a, b), _t(x), _t(y), name="equal_all")


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                 _t(x), _t(y), name="allclose")


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                 _t(x), _t(y), name="isclose")


def logical_and(x, y, out=None, name=None):
    return _cmp(jnp.logical_and, x, y, "logical_and")


def logical_or(x, y, out=None, name=None):
    return _cmp(jnp.logical_or, x, y, "logical_or")


def logical_xor(x, y, out=None, name=None):
    return _cmp(jnp.logical_xor, x, y, "logical_xor")


def logical_not(x, out=None, name=None):
    return apply(jnp.logical_not, _t(x), name="logical_not")


def bitwise_and(x, y, out=None, name=None):
    return _cmp(jnp.bitwise_and, x, y, "bitwise_and")


def bitwise_or(x, y, out=None, name=None):
    return _cmp(jnp.bitwise_or, x, y, "bitwise_or")


def bitwise_xor(x, y, out=None, name=None):
    return _cmp(jnp.bitwise_xor, x, y, "bitwise_xor")


def bitwise_not(x, out=None, name=None):
    return apply(jnp.bitwise_not, _t(x), name="bitwise_not")


def is_empty(x, name=None):
    return Tensor(np.bool_(_t(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    return apply(lambda c, a, b: jnp.where(c, a, b), _t(condition), _t(x), _t(y), name="where")


def masked_select(x, mask, name=None):
    # Data-dependent output shape: host round-trip (eager only).
    arr = np.asarray(_t(x).data)
    m = np.asarray(_t(mask).data).astype(bool)
    return Tensor(arr[m])


def nonzero(x, as_tuple=False):
    arr = np.asarray(_t(x).data)
    idx = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(i.astype(np.int64)) for i in idx)
    return Tensor(np.stack(idx, axis=1).astype(np.int64))
