"""nn.quant: fake-quantization layers for QAT graphs.

reference parity: python/paddle/nn/quant/quant_layers.py —
FakeQuantAbsMax(:60), FakeQuantMovingAverageAbsMax(:119),
FakeQuantChannelWiseAbsMax(:204), MovingAverageAbsMaxScale(:281),
QuantizedConv2D(:344), QuantizedLinear(:511), MAOutputScaleLayer,
FloatFunctionalLayer (functional_layers.py).

TPU-native: every fake-quant is a quantize-dequantize with a
straight-through gradient (stop_gradient residual), so the whole QAT
graph stays jit-compilable; moving-average ranges live in buffers
updated on the eager tape (and frozen under jit, matching the
reference's is_test behavior). The deploy conversion lives in
paddle_tpu.slim (QuantizedLinear with real int8 storage), which runs
the Pallas int8 x int8 matmul (ops.pallas.quant_matmul) on the grid
:class:`PerChannelAbsMaxObserver` records — one symmetric-absmax scale
rule shared by the QAT layers, the slim deploy pass and the kernel
(docs/PARITY.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, apply
from ..layer import Layer

__all__ = [
    "FakeQuantAbsMax", "FakeQuantChannelWiseAbsMax",
    "FakeQuantMovingAverageAbsMax", "MovingAverageAbsMaxScale",
    "PerChannelAbsMaxObserver",
    "QuantizedLinear", "QuantizedConv2D", "QuantizedConv2DTranspose",
    "MAOutputScaleLayer", "FakeQuantMAOutputScaleLayer",
    "FloatFunctionalLayer", "add", "subtract", "multiply", "divide",
]


def _qdq(a, scale, qmax):
    q = jnp.clip(jnp.round(a / scale), -qmax, qmax) * scale
    return a + jax.lax.stop_gradient(q - a)     # straight-through grad


class PerChannelAbsMaxObserver:
    """Per-channel symmetric-absmax weight observer — THE scale rule of
    the int8 stack (reference: the channel_wise_abs_max observer behind
    slim's WeightQuantization). ``observe(w)`` records and returns the
    per-channel scales ``absmax / (2^(bits-1) - 1)`` along
    ``quant_axis``; ``quantize(w)`` returns the int8 weights + scales on
    that grid. Host-side (numpy): observation happens at deploy
    conversion, not inside traced programs. slim._channel_scales and
    the Pallas kernel's ``quantize_per_channel`` both follow this rule —
    tests pin they agree.
    """

    def __init__(self, quant_bits: int = 8, quant_axis: int = 1,
                 eps: float = 1e-8):
        self.quant_bits = int(quant_bits)
        self.quant_axis = int(quant_axis)
        self.eps = float(eps)
        self.scales = None

    @property
    def qmax(self) -> float:
        return 2.0 ** (self.quant_bits - 1) - 1

    def observe(self, w) -> np.ndarray:
        """Record per-channel scales of ``w`` (accumulating the running
        absmax across calls, PTQ-style); returns the scales [channels]."""
        w = np.asarray(w, np.float32)
        red = tuple(i for i in range(w.ndim) if i != self.quant_axis)
        absmax = np.abs(w).max(axis=red)
        if self.scales is not None:
            absmax = np.maximum(absmax, self.scales * self.qmax)
        self.scales = np.maximum(absmax / self.qmax, self.eps) \
            .astype(np.float32)
        return self.scales

    def quantize(self, w):
        """(w_q int8, scales f32) on the observed grid (observes ``w``
        first when no scales were recorded yet)."""
        w = np.asarray(w, np.float32)
        scales = self.scales if self.scales is not None else self.observe(w)
        shape = [1] * w.ndim
        shape[self.quant_axis] = -1
        q = np.clip(np.round(w / scales.reshape(shape)),
                    -self.qmax, self.qmax).astype(np.int8)
        return q, scales


class FakeQuantAbsMax(Layer):
    """Per-tensor absmax fake quant (reference: quant_layers.py:60)."""

    def __init__(self, name=None, quant_bits=8, dtype="float32"):
        super().__init__()
        self.quant_bits = quant_bits

    def forward(self, x):
        qmax = 2.0 ** (self.quant_bits - 1) - 1

        def _fq(a):
            s = jnp.maximum(jnp.max(jnp.abs(a)) / qmax, 1e-9)
            return _qdq(a, s, qmax)

        return apply(_fq, x, name="fake_quantize_abs_max")


class FakeQuantChannelWiseAbsMax(Layer):
    """Per-channel absmax fake quant (reference: quant_layers.py:204)."""

    def __init__(self, name=None, channel_num=None, quant_bits=8,
                 quant_axis=0, dtype="float32"):
        super().__init__()
        self.quant_bits = quant_bits
        self.quant_axis = quant_axis

    def forward(self, x):
        qmax = 2.0 ** (self.quant_bits - 1) - 1
        axis = self.quant_axis

        def _fq(a):
            red = tuple(i for i in range(a.ndim) if i != axis)
            s = jnp.maximum(jnp.max(jnp.abs(a), axis=red, keepdims=True)
                            / qmax, 1e-9)
            return _qdq(a, s, qmax)

        return apply(_fq, x, name="fake_channel_wise_quantize_abs_max")


class FakeQuantMovingAverageAbsMax(Layer):
    """Moving-average absmax fake quant (reference: quant_layers.py:119):
    the activation range is an EMA buffer updated in training mode."""

    def __init__(self, name=None, moving_rate=0.9, quant_bits=8,
                 dtype="float32"):
        super().__init__()
        self.moving_rate = moving_rate
        self.quant_bits = quant_bits
        self.register_buffer("scale", Tensor(jnp.ones((), jnp.float32)),
                             persistable=True)
        self.register_buffer("state", Tensor(jnp.ones((), jnp.float32)),
                             persistable=True)

    def update_range(self, x):
        """EMA absmax update (shared with the pure observer layer)."""
        rate = self.moving_rate

        def _update(a, sc, st):
            # range tracking is state, not a gradient path
            absmax = jnp.max(jnp.abs(jax.lax.stop_gradient(a)))
            st2 = st * rate + 1.0
            sc2 = (sc * rate * st + absmax) / st2
            return jax.lax.stop_gradient(sc2), jax.lax.stop_gradient(st2)

        sc2, st2 = apply(_update, x, self.scale, self.state,
                         name="moving_average_abs_max_update")
        from ...core.tensor import annotate_test_variant, record_mutation
        annotate_test_variant(lambda a, sc, st: (sc, st))  # frozen at eval
        record_mutation(self.scale, sc2)
        record_mutation(self.state, st2)

    def forward(self, x):
        qmax = 2.0 ** (self.quant_bits - 1) - 1
        if self.training:
            self.update_range(x)

        def _fq(a, sc):
            s = jnp.maximum(sc / qmax, 1e-9)
            return _qdq(a, s, qmax)

        return apply(_fq, x, self.scale,
                     name="fake_quantize_moving_average_abs_max")


class MovingAverageAbsMaxScale(Layer):
    """Observe (EMA absmax) without quantizing (reference:
    quant_layers.py:281) — used to record output scales for deploy."""

    def __init__(self, name=None, moving_rate=0.9, dtype="float32"):
        super().__init__()
        self._fq = FakeQuantMovingAverageAbsMax(moving_rate=moving_rate)

    @property
    def scale(self):
        return self._fq.scale

    def forward(self, x):
        if self.training:
            self._fq.update_range(x)    # observe only, no quantize pass
        return x


class QuantizedLinear(Layer):
    """QAT wrapper over nn.Linear (reference: quant_layers.py:511)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="channel_wise_abs_max",
                 activation_quantize_type="moving_average_abs_max", **kw):
        super().__init__()
        self.inner = layer
        if weight_quantize_type == "channel_wise_abs_max":
            self._fq_w = FakeQuantChannelWiseAbsMax(quant_bits=weight_bits,
                                                    quant_axis=1)
        else:
            self._fq_w = FakeQuantAbsMax(quant_bits=weight_bits)
        self._fq_a = FakeQuantMovingAverageAbsMax(moving_rate=moving_rate,
                                                  quant_bits=activation_bits)

    def forward(self, x):
        from .. import functional as F
        return F.linear(self._fq_a(x), self._fq_w(self.inner.weight),
                        self.inner.bias)


class QuantizedConv2D(Layer):
    """QAT wrapper over nn.Conv2D (reference: quant_layers.py:344)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, **kw):
        super().__init__()
        self.inner = layer
        self._fq_w = FakeQuantChannelWiseAbsMax(quant_bits=weight_bits,
                                                quant_axis=0)
        self._fq_a = FakeQuantMovingAverageAbsMax(moving_rate=moving_rate,
                                                  quant_bits=activation_bits)

    def forward(self, x):
        from .. import functional as F
        inner = self.inner
        return F.conv2d(self._fq_a(x), self._fq_w(inner.weight), inner.bias,
                        stride=inner._stride, padding=inner._padding,
                        dilation=inner._dilation, groups=inner._groups,
                        data_format=inner._data_format)


class QuantizedConv2DTranspose(Layer):
    """QAT wrapper over nn.Conv2DTranspose (reference: quant_layers.py).
    Transpose-conv filters are (in, out//groups, kh, kw): output channels
    live on axis 1, so channel-wise scales quantize along quant_axis=1."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, **kw):
        super().__init__()
        self.inner = layer
        self._fq_w = FakeQuantChannelWiseAbsMax(quant_bits=weight_bits,
                                                quant_axis=1)
        self._fq_a = FakeQuantMovingAverageAbsMax(moving_rate=moving_rate,
                                                  quant_bits=activation_bits)

    def forward(self, x):
        from .. import functional as F
        inner = self.inner
        return F.conv2d_transpose(
            self._fq_a(x), self._fq_w(inner.weight), inner.bias,
            stride=inner._stride, padding=inner._padding,
            dilation=inner._dilation, groups=inner._groups,
            output_padding=getattr(inner, "_output_padding", 0),
            data_format=inner._data_format)


class MAOutputScaleLayer(Layer):
    """Wrap a layer and observe its output scale (reference:
    quant_layers.py MAOutputScaleLayer)."""

    def __init__(self, layer, moving_rate=0.9, name=None, dtype="float32"):
        super().__init__()
        self.inner = layer
        self._scale = MovingAverageAbsMaxScale(moving_rate=moving_rate)

    def forward(self, *args, **kwargs):
        out = self.inner(*args, **kwargs)
        return self._scale(out)


class FakeQuantMAOutputScaleLayer(Layer):
    """Wrap a layer, fake-quantizing its output with an EMA range."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, name=None, **kw):
        super().__init__()
        self.inner = layer
        self._fq = FakeQuantMovingAverageAbsMax(moving_rate=moving_rate,
                                                quant_bits=activation_bits)

    def forward(self, *args, **kwargs):
        return self._fq(self.inner(*args, **kwargs))


class FloatFunctionalLayer(Layer):
    """Elementwise ops as layers so quant passes can hook them
    (reference: nn/quant/functional_layers.py)."""

    def __init__(self):
        super().__init__()


def _make_functional(opname):
    class _Op(FloatFunctionalLayer):
        def forward(self, x, y, name=None):
            from ... import tensor as T
            return getattr(T, opname)(x, y)
    _Op.__name__ = opname
    return _Op


add = _make_functional("add")
subtract = _make_functional("subtract")
multiply = _make_functional("multiply")
divide = _make_functional("divide")
