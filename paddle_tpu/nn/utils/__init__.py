"""nn.utils: weight reparametrizations + parameter/vector helpers.

reference parity: python/paddle/nn/utils/weight_norm_hook.py
(WeightNorm:32 — g * v/||v|| recomputed every forward via a pre-hook),
spectral_norm_hook.py, and paddle.nn.utils.parameters_to_vector /
vector_to_parameters (nn/utils/transform_parameters.py).

TPU-native: the hook recomputes the effective weight INSIDE the traced
forward, so under jit the renormalization fuses into the step (no
eager-side mutation); g and v are the leaf parameters the optimizer and
ZeRO sharding see.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ...core.tensor import Tensor
from ..layer import Layer

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters"]


def _norm_except(v, dim: int):
    axes = tuple(i for i in range(v.ndim) if i != dim)

    def _n(a):
        return jnp.sqrt(jnp.sum(a.astype(jnp.float32) ** 2, axis=axes,
                                keepdims=True))

    from ...core.tensor import apply
    return apply(_n, v, name="weight_norm_norm")


class _WeightNormHook:
    def __init__(self, name: str, dim: int):
        self.name = name
        self.dim = dim

    def __call__(self, layer, inputs):
        g = getattr(layer, self.name + "_g")
        v = getattr(layer, self.name + "_v")
        norm = _norm_except(v, self.dim)
        from ...core.tensor import apply
        w = apply(lambda gv, vv, nv: ((gv / nv)
                                      * vv.astype(jnp.float32))
                  .astype(vv.dtype),
                  g, v, norm, name="weight_norm_apply")
        object.__setattr__(layer, self.name, w)
        return None


def weight_norm(layer: Layer, name: str = "weight", dim: int = 0) -> Layer:
    """Reparametrize `layer.<name>` as g * v / ||v|| (reference:
    nn/utils/weight_norm_hook.py weight_norm)."""
    w = layer._parameters.pop(name)
    v = layer.create_parameter(tuple(w.shape), dtype=str(w.dtype))
    v._data = w._data
    layer.add_parameter(name + "_v", v)
    norm = _norm_except(v, dim)
    g = layer.create_parameter(tuple(norm.shape), dtype="float32")
    g._data = norm._data
    layer.add_parameter(name + "_g", g)
    setattr(layer, "_wn_hook_" + name,
            layer.register_forward_pre_hook(_WeightNormHook(name, dim)))
    setattr(layer, "_wn_dim_" + name, dim)
    # materialize once so layer.weight exists before the first forward
    _WeightNormHook(name, dim)(layer, ())
    return layer


def remove_weight_norm(layer: Layer, name: str = "weight") -> Layer:
    """Fold g*v/||v|| back into a plain parameter (reference:
    remove_weight_norm)."""
    remover = getattr(layer, "_wn_hook_" + name, None)
    if remover is None:
        raise ValueError(f"{name!r} is not weight-normed on {layer}")
    remover.remove()
    dim = getattr(layer, "_wn_dim_" + name, 0)
    delattr(layer, "_wn_hook_" + name)
    if hasattr(layer, "_wn_dim_" + name):
        delattr(layer, "_wn_dim_" + name)
    g = layer._parameters.pop(name + "_g")
    v = layer._parameters.pop(name + "_v")
    norm = _norm_except(v, dim)
    w = layer.create_parameter(tuple(v.shape), dtype=str(v.dtype))
    w._data = ((g._data / norm._data) * v._data.astype(jnp.float32)) \
        .astype(v._data.dtype)
    if hasattr(layer, name):           # drop the hook-era plain attribute
        try:
            object.__delattr__(layer, name)
        except AttributeError:
            pass
    layer.add_parameter(name, w)
    return layer


def spectral_norm(layer: Layer, name: str = "weight", n_power_iterations=1,
                  eps: float = 1e-12, dim: int = 0) -> Layer:
    """Spectral normalization via the SpectralNorm layer's math applied as
    a pre-hook (reference: nn/utils/spectral_norm_hook.py)."""
    w = getattr(layer, name)
    shape = tuple(w.shape)
    h = shape[dim]
    rng = np.random.default_rng(0)
    u0 = Tensor(jnp.asarray(rng.normal(size=(h,)).astype(np.float32)))
    # persistent power-iteration state: warm-started every forward so
    # sigma converges across steps (reference keeps u as a buffer)
    layer.register_buffer("_sn_u_" + name, u0, persistable=True)

    def hook(lyr, inputs):
        import jax as _jax

        from ...core.tensor import apply
        wv = lyr._parameters[name + "_orig"]
        u = lyr._buffers["_sn_u_" + name]

        def _sn(a, uu):
            mat = jnp.moveaxis(a.astype(jnp.float32), dim, 0).reshape(h, -1)
            uv = uu
            for _ in range(n_power_iterations):
                vv = mat.T @ uv
                vv = vv / (jnp.linalg.norm(vv) + eps)
                uv = mat @ vv
                uv = uv / (jnp.linalg.norm(uv) + eps)
            sigma = uv @ mat @ vv
            return ((a.astype(jnp.float32) / sigma).astype(a.dtype),
                    _jax.lax.stop_gradient(uv))

        eff, u_new = apply(_sn, wv, u, name="spectral_norm_apply")
        from ...core.tensor import record_mutation
        record_mutation(u, u_new)
        object.__setattr__(lyr, name, eff)
        return None

    layer._parameters[name + "_orig"] = layer._parameters.pop(name)
    layer.register_forward_pre_hook(hook)
    hook(layer, ())
    return layer


def parameters_to_vector(parameters, name=None) -> Tensor:
    """Flatten-concat parameters (reference:
    nn/utils/transform_parameters.py)."""
    from ...core.tensor import apply
    params = list(parameters)

    def _cat(*arrs):
        return jnp.concatenate([a.reshape(-1) for a in arrs])

    return apply(_cat, *params, name="parameters_to_vector")


def vector_to_parameters(vec: Tensor, parameters) -> None:
    """Write a flat vector back into parameters in order."""
    data = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    off = 0
    for p in parameters:
        n = int(np.prod(p.shape))
        p._data = data[off:off + n].reshape(tuple(p.shape)) \
            .astype(p._data.dtype)
        off += n
    if off != data.shape[0]:
        raise ValueError(f"vector length {data.shape[0]} != total "
                         f"parameter size {off}")
