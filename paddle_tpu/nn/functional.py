"""Functional NN ops (reference surface: python/paddle/nn/functional/).

All ops are jnp/lax compositions routed through core.tensor.apply so both
the eager tape and jit tracing work. Convs/matmuls hit the MXU via
lax.conv_general_dilated / jnp.matmul; XLA fuses the elementwise epilogues.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtypes
from ..core.flags import matmul_precision
from ..core.random import in_trace_rng, make_rng
from ..core.tensor import (Tensor, annotate_test_variant, apply,
                           record_mutation)
from . import layout as _layout

__all__ = [
    # activations
    "relu", "relu6", "leaky_relu", "prelu", "elu", "selu", "celu", "gelu",
    "sigmoid", "hardsigmoid", "hardswish", "hardtanh", "hardshrink",
    "softshrink", "tanhshrink", "swish", "silu", "mish", "softplus",
    "softsign", "tanh", "log_sigmoid", "maxout", "glu", "rrelu",
    # softmax family
    "softmax", "log_softmax", "gumbel_softmax",
    # linear / conv
    "linear", "conv1d", "conv2d", "conv3d", "conv1d_transpose",
    "conv2d_transpose", "conv3d_transpose",
    # pooling
    "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
    "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
    # norm
    "batch_norm", "fused_conv_bn", "layer_norm", "instance_norm",
    "group_norm", "local_response_norm", "normalize",
    # dropout
    "dropout", "dropout2d", "dropout3d", "alpha_dropout",
    # embedding / one-hot
    "embedding", "one_hot",
    # losses
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "nll_loss",
    "kl_div", "smooth_l1_loss", "margin_ranking_loss", "ctc_loss",
    "hinge_embedding_loss", "cosine_embedding_loss", "sigmoid_focal_loss",
    "square_error_cost", "log_loss", "npair_loss", "triplet_margin_loss",
    # shape ops
    "pad", "interpolate", "upsample", "pixel_shuffle", "pixel_unshuffle",
    "unfold", "fold", "affine_grid", "grid_sample",
    # misc
    "cosine_similarity", "label_smooth", "sequence_mask", "temporal_shift",
    "class_center_sample", "scaled_dot_product_attention", "sparse_attention",
    "adaptive_max_pool3d", "max_pool2d_with_index", "max_unpool2d",
    "pairwise_distance", "hsigmoid_loss",
]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(x)


# ---------------------------------------------------------------------------
# Activations
# ---------------------------------------------------------------------------

def _unary(fn, name):
    def op(x, name=None):
        return apply(fn, _t(x), name=name or op.__name__)
    op.__name__ = name
    return op


relu = _unary(jax.nn.relu, "relu")
relu6 = _unary(jax.nn.relu6, "relu6")
sigmoid = _unary(jax.nn.sigmoid, "sigmoid")
tanh = _unary(jnp.tanh, "tanh")
softsign = _unary(jax.nn.soft_sign, "softsign")
silu = _unary(jax.nn.silu, "silu")
log_sigmoid = _unary(jax.nn.log_sigmoid, "log_sigmoid")
mish = _unary(lambda x: x * jnp.tanh(jax.nn.softplus(x)), "mish")
tanhshrink = _unary(lambda x: x - jnp.tanh(x), "tanhshrink")


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(lambda a: jax.nn.leaky_relu(a, negative_slope), _t(x), name="leaky_relu")


def prelu(x, weight, data_format="NCHW", name=None):
    def _prelu(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        shape = [1] * a.ndim
        ch_axis = 1 if data_format[1] == "C" else a.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)
    return apply(_prelu, _t(x), _t(weight), name="prelu")


def elu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.elu(a, alpha), _t(x), name="elu")


def celu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.celu(a, alpha), _t(x), name="celu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)),
                 _t(x), name="selu")


def gelu(x, approximate=False, name=None):
    return apply(lambda a: jax.nn.gelu(a, approximate=approximate), _t(x), name="gelu")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply(lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), _t(x), name="hardsigmoid")


def hardswish(x, name=None):
    return apply(lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, _t(x), name="hardswish")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(lambda a: jnp.clip(a, min, max), _t(x), name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return apply(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), _t(x), name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    return apply(lambda a: jnp.where(a > threshold, a - threshold,
                                     jnp.where(a < -threshold, a + threshold, 0.0)),
                 _t(x), name="softshrink")


def swish(x, name=None):
    return silu(x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(lambda a: jnp.where(beta * a > threshold, a,
                                     jnp.log1p(jnp.exp(beta * a)) / beta),
                 _t(x), name="softplus")


def maxout(x, groups, axis=1, name=None):
    def _maxout(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (groups, c // groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)
    return apply(_maxout, _t(x), name="maxout")


def glu(x, axis=-1, name=None):
    def _glu(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)
    return apply(_glu, _t(x), name="glu")


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    if training:
        key = make_rng()
        def _rr(a):
            slope = jax.random.uniform(key, a.shape, a.dtype, lower, upper)
            return jnp.where(a >= 0, a, slope * a)
        return apply(_rr, _t(x), name="rrelu")
    mid = (lower + upper) / 2.0
    return leaky_relu(x, mid)


# ---------------------------------------------------------------------------
# Softmax family
# ---------------------------------------------------------------------------

def softmax(x, axis=-1, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype)
    def _sm(a):
        if d is not None:
            a = a.astype(d)
        return jax.nn.softmax(a, axis=axis)
    return apply(_sm, _t(x), name="softmax",
                 _cache_token=("softmax", axis, str(d)))


def log_softmax(x, axis=-1, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype)
    def _lsm(a):
        if d is not None:
            a = a.astype(d)
        return jax.nn.log_softmax(a, axis=axis)
    return apply(_lsm, _t(x), name="log_softmax",
                 _cache_token=("log_softmax", axis, str(d)))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    key = make_rng()
    def _gs(a):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            y_hard = jax.nn.one_hot(jnp.argmax(y, axis=axis), a.shape[axis],
                                    axis=axis, dtype=a.dtype)
            y = y_hard - jax.lax.stop_gradient(y) + y
        return y
    return apply(_gs, _t(x), name="gumbel_softmax")


# ---------------------------------------------------------------------------
# Linear / conv — the MXU path
# ---------------------------------------------------------------------------

def _amp_int8_active(weight_t) -> bool:
    """FLAGS_amp_int8_matmul routing gate for :func:`linear`: only under
    an ACTIVE amp.auto_cast region, with the Pallas int8 kernel enabled
    and a 2-D weight the kernel can tile. Resolved at dispatch time
    (before any trace) and folded into the op-cache token, so a cached
    f32 linear can never serve an int8 call or vice versa."""
    from ..core.flags import get_flag
    if not get_flag("amp_int8_matmul"):
        return False
    from ..amp.auto_cast import amp_state
    st = amp_state()
    if st is None or not st.enabled:
        return False
    from ..ops import pallas as pallas_ops
    if not pallas_ops.kernel_enabled("int8_matmul"):
        return False
    if weight_t.ndim != 2:
        return False
    from ..ops.pallas.quant_matmul import matmul_shapes_supported
    if not matmul_shapes_supported(int(weight_t.shape[0]),
                                   int(weight_t.shape[1])):
        pallas_ops.note_fallback("int8_matmul", "shape")
        return False
    return True


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b. Weight layout [in, out] (reference: nn/functional/common.py linear).

    Under ``FLAGS_amp_int8_matmul`` (+ an active autocast region) the
    matmul runs through the Pallas int8 kernel with dynamically
    quantized operands and a straight-through dense backward
    (ops.pallas.quant_matmul.int8_amp_linear) — an experimental
    throughput knob, off by default."""
    prec = matmul_precision()
    w_t = _t(weight)
    if _amp_int8_active(w_t):
        from ..ops.pallas.quant_matmul import int8_amp_linear
        if bias is None:
            return apply(lambda a, w: int8_amp_linear(a, w),
                         _t(x), w_t, name="linear",
                         _cache_token=("linear_int8",))
        return apply(lambda a, w, b: int8_amp_linear(a, w, b),
                     _t(x), w_t, _t(bias), name="linear",
                     _cache_token=("linear_int8",))
    if bias is None:
        return apply(lambda a, w: jnp.matmul(a, w, precision=prec),
                     _t(x), w_t, name="linear",
                     _cache_token=("linear", str(prec)))
    return apply(lambda a, w, b: jnp.matmul(a, w, precision=prec) + b,
                 _t(x), w_t, _t(bias), name="linear",
                 _cache_token=("linear", str(prec)))


def _norm_tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(i) for i in v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
def _conv_accum_f32(a, w, stride, pad, lhs_dilation, rhs_dilation, dn,
                    groups):
    """Low-precision conv with EXPLICIT f32 accumulation: bf16/f16 operands
    stream through the MXU, the accumulator is pinned to f32 via
    ``preferred_element_type``, and the result is rounded back to the
    activation dtype — the production AMP conv contract, stated in the HLO
    instead of left to backend defaults.

    The custom VJP exists because ``preferred_element_type`` breaks jax's
    conv transpose rules under autodiff (the rhs rule feeds the f32
    cotangent into a conv against bf16 primals and lax rejects the mixed
    dtypes). The backward therefore differentiates the PLAIN low-precision
    conv — its cotangents are already in the activation dtype, and the two
    backward convs get the same implicit f32 accumulation from XLA:TPU.
    """
    out = jax.lax.conv_general_dilated(
        a, w, window_strides=stride, padding=pad,
        lhs_dilation=lhs_dilation, rhs_dilation=rhs_dilation,
        dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=jnp.float32)
    return out.astype(a.dtype)


def _conv_accum_f32_fwd(a, w, stride, pad, lhs_dilation, rhs_dilation, dn,
                        groups):
    out = _conv_accum_f32(a, w, stride, pad, lhs_dilation, rhs_dilation,
                          dn, groups)
    return out, (a, w)


def _conv_accum_f32_bwd(stride, pad, lhs_dilation, rhs_dilation, dn, groups,
                        res, g):
    a, w = res

    def plain(a_, w_):
        return jax.lax.conv_general_dilated(
            a_, w_, window_strides=stride, padding=pad,
            lhs_dilation=lhs_dilation, rhs_dilation=rhs_dilation,
            dimension_numbers=dn, feature_group_count=groups)

    _, vjp = jax.vjp(plain, a, w)
    return vjp(g.astype(a.dtype))


_conv_accum_f32.defvjp(_conv_accum_f32_fwd, _conv_accum_f32_bwd)


def _run_conv(a, w, stride, pad, lhs_dilation, rhs_dilation, dn, groups):
    """Dispatch one conv: explicit-f32-accumulation path for the bf16/f16
    activation stream (AMP), plain conv for full precision."""
    if a.dtype in (jnp.bfloat16, jnp.float16):
        return _conv_accum_f32(a, w.astype(a.dtype), stride, pad,
                               lhs_dilation, rhs_dilation, dn, groups)
    return jax.lax.conv_general_dilated(
        a, w, window_strides=stride, padding=pad,
        lhs_dilation=lhs_dilation, rhs_dilation=rhs_dilation,
        dimension_numbers=dn, feature_group_count=groups)


def _conv_specs(n, channel_last):
    if channel_last:
        return {1: ("NWC", "OIW", "NWC"), 2: ("NHWC", "OIHW", "NHWC"),
                3: ("NDHWC", "OIDHW", "NDHWC")}[n]
    return {1: ("NCW", "OIW", "NCW"), 2: ("NCHW", "OIHW", "NCHW"),
            3: ("NCDHW", "OIDHW", "NCDHW")}[n]


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, data_format, n):
    """Shared conv implementation over lax.conv_general_dilated."""
    stride = _norm_tuple(stride, n)
    dilation = _norm_tuple(dilation, n)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    x = _t(x)
    # channels-last planner (nn.layout): inside an active scope a 2-D NCHW
    # conv runs NHWC-native — the first conv in the chain pays the ONE
    # entry transpose, everything downstream consumes the tag
    internal_cl = (n == 2 and not channel_last and _layout.is_active())
    if internal_cl:
        if x._layout != "NHWC":
            x = _layout.to_channels_last(x)
        channel_last = True
    spec = _conv_specs(n, channel_last)

    if isinstance(padding, str):
        pad = padding.upper()  # 'SAME' | 'VALID'
    else:
        p = _norm_tuple(padding, n) if not (isinstance(padding, (list, tuple)) and
                                            isinstance(padding[0], (list, tuple))) else padding
        if isinstance(p[0], (list, tuple)):
            pad = tuple(tuple(pp) for pp in p)
        else:
            pad = tuple((pi, pi) for pi in p)

    def _conv(a, w, *maybe_bias):
        out = _run_conv(a, w, stride, pad, None, dilation, spec, groups)
        if maybe_bias:
            b = maybe_bias[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = b.size
            out = out + b.reshape(shape).astype(out.dtype)
        return out

    args = (x, _t(weight)) + ((_t(bias),) if bias is not None else ())
    out = apply(_conv, *args, name=f"conv{n}d",
                _cache_token=("conv", n, stride, pad, dilation, groups,
                              spec))
    if internal_cl:
        _layout.tag(out)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NLC" if data_format == "NLC" else "NCL"
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups,
                    "NLC" if fmt == "NLC" else "NCW", 1)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, data_format, 2)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, data_format, 3)


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, data_format, n, output_size=None):
    """Transposed conv as a fractionally-strided conv: dilate the input by
    `stride` (lhs_dilation), flip the kernel, swap its in/out channels, and
    run a regular conv with padding (k_eff-1-p). Matches the reference's
    output-size formula (H-1)*s - 2p + d*(k-1) + 1 + output_padding."""
    stride = _norm_tuple(stride, n)
    dilation = _norm_tuple(dilation, n)
    pads_in = _norm_tuple(padding, n) if not isinstance(padding, str) else None
    opad = _norm_tuple(output_padding, n) if output_padding else (0,) * n
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    spec = _conv_specs(n, channel_last)

    def _convt(a, w, *maybe_bias):
        # w layout: [in_c, out_c/groups, *k] (reference conv_transpose layout)
        in_c = w.shape[0]
        outg = w.shape[1]
        k_spatial = w.shape[2:]
        g = groups
        w_ = w.reshape((g, in_c // g, outg) + k_spatial)
        w_ = jnp.swapaxes(w_, 1, 2)  # [g, out/g, in/g, *k]
        w_ = w_.reshape((g * outg, in_c // g) + k_spatial)
        w_ = jnp.flip(w_, axis=tuple(range(2, 2 + n)))

        if pads_in is None:  # 'SAME'/'VALID' string: treat as zero padding
            p_eff = (0,) * n
        else:
            p_eff = pads_in
        conv_pads = []
        for i in range(n):
            k_eff = (k_spatial[i] - 1) * dilation[i] + 1
            lo = k_eff - 1 - p_eff[i]
            hi = k_eff - 1 - p_eff[i] + opad[i]
            conv_pads.append((lo, hi))

        # same explicit-f32-accumulation contract as the forward conv —
        # and, via _conv_accum_f32's custom VJP, a backward that actually
        # differentiates under the bf16 activation stream (the raw
        # preferred_element_type form broke the conv transpose rule)
        out = _run_conv(a, w_, (1,) * n, tuple(conv_pads), stride, dilation,
                        spec, g)
        if maybe_bias:
            b = maybe_bias[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = b.size
            out = out + b.reshape(shape).astype(out.dtype)
        return out

    args = (_t(x), _t(weight)) + ((_t(bias),) if bias is not None else ())
    return apply(_convt, *args, name=f"conv{n}d_transpose",
                 _cache_token=("convt", n, stride, dilation, pads_in, opad,
                               groups, spec))


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, "NLC" if data_format == "NLC" else "NCW", 1)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, data_format, 2, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, data_format, 3, output_size)


# ---------------------------------------------------------------------------
# Pooling
# ---------------------------------------------------------------------------

def _pool_nd(x, kernel_size, stride, padding, n, reducer, init, data_format,
             ceil_mode=False, count_include_pad=True, divisor_override=None):
    ks = _norm_tuple(kernel_size, n)
    st = _norm_tuple(stride if stride is not None else kernel_size, n)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    x = _t(x)
    # consume the channels-last planner tag: the pool runs NHWC-native
    # with no transpose on either side
    internal_cl = (n == 2 and not channel_last and _layout.is_active()
                   and x._layout == "NHWC")
    if internal_cl:
        channel_last = True
    if isinstance(padding, str):
        pad_mode = padding.upper()
        pads = None
    else:
        pad_mode = None
        pads = _norm_tuple(padding, n)

    def _pool(a):
        if channel_last:
            window = (1,) + ks + (1,)
            strides = (1,) + st + (1,)
            spatial = range(1, 1 + n)
        else:
            window = (1, 1) + ks
            strides = (1, 1) + st
            spatial = range(2, 2 + n)
        if pads is None:
            padding_cfg = pad_mode
        else:
            padding_cfg = [(0, 0)] * a.ndim
            for i, d in enumerate(spatial):
                padding_cfg[d] = (pads[i], pads[i])
        if reducer == "max":
            neg = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
            return jax.lax.reduce_window(a, neg, jax.lax.max, window, strides, padding_cfg)
        # avg
        summed = jax.lax.reduce_window(a.astype(jnp.float32), 0.0, jax.lax.add,
                                       window, strides, padding_cfg)
        if divisor_override:
            return (summed / divisor_override).astype(a.dtype)
        if count_include_pad or (pads is None or not any(pads)):
            denom = float(np.prod(ks))
            return (summed / denom).astype(a.dtype)
        ones = jnp.ones_like(a, jnp.float32)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, padding_cfg)
        return (summed / counts).astype(a.dtype)

    out = apply(_pool, x, name=f"{reducer}_pool{n}d",
                _cache_token=("pool", n, ks, st, pad_mode, pads, reducer,
                              channel_last, count_include_pad,
                              divisor_override))
    if internal_cl:
        _layout.tag(out)
    return out


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, "max", None, "NCW", ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    if return_mask:
        if data_format != "NCHW" or ceil_mode:
            raise ValueError("return_mask supports NCHW, ceil_mode=False")
        return max_pool2d_with_index(x, kernel_size, stride, padding)
    return _pool_nd(x, kernel_size, stride, padding, 2, "max", None, data_format, ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, "max", None, data_format, ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool_nd(x, kernel_size, stride, padding, 1, "avg", None, "NCW",
                    ceil_mode, count_include_pad=not exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 2, "avg", None, data_format,
                    ceil_mode, count_include_pad=not exclusive,
                    divisor_override=divisor_override)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, "avg", None, data_format,
                    ceil_mode, count_include_pad=not exclusive,
                    divisor_override=divisor_override)


def _adaptive_pool(x, output_size, n, mode, data_format):
    out_sizes = _norm_tuple(output_size, n)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    x = _t(x)
    internal_cl = (n == 2 and not channel_last and _layout.is_active()
                   and x._layout == "NHWC")
    if internal_cl:
        channel_last = True

    def _ap(a):
        spatial0 = 1 if channel_last else 2
        out = a
        for i, osz in enumerate(out_sizes):
            ax = spatial0 + i
            isz = out.shape[ax]
            if osz is None or osz == isz:
                continue
            if isz % osz == 0:
                k = isz // osz
                new_shape = out.shape[:ax] + (osz, k) + out.shape[ax + 1:]
                r = out.reshape(new_shape)
                out = jnp.max(r, axis=ax + 1) if mode == "max" else jnp.mean(r, axis=ax + 1)
            else:
                # general adaptive: gather per output bin
                starts = (np.arange(osz) * isz) // osz
                ends = ((np.arange(osz) + 1) * isz + osz - 1) // osz
                slices = []
                for s, e in zip(starts, ends):
                    seg = jax.lax.slice_in_dim(out, int(s), int(e), axis=ax)
                    red = jnp.max(seg, axis=ax, keepdims=True) if mode == "max" \
                        else jnp.mean(seg, axis=ax, keepdims=True)
                    slices.append(red)
                out = jnp.concatenate(slices, axis=ax)
        return out

    out = apply(_ap, x, name=f"adaptive_{mode}_pool{n}d",
                _cache_token=("apool", n, out_sizes, mode, channel_last))
    if internal_cl:
        _layout.tag(out)
    return out


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "avg", "NCW")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError(
            "adaptive_max_pool1d: return_mask not supported; use "
            "max_pool2d_with_index for pooled indices")
    return _adaptive_pool(x, output_size, 1, "max", "NCW")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError(
            "adaptive_max_pool2d: return_mask not supported; use "
            "max_pool2d_with_index for pooled indices")
    return _adaptive_pool(x, output_size, 2, "max", "NCHW")




def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError(
            "adaptive_max_pool3d: return_mask not supported; use "
            "max_pool2d_with_index for pooled indices")
    return _adaptive_pool(x, output_size, 3, "max", "NCDHW")


def max_pool2d_with_index(x, kernel_size, stride=None, padding=0, name=None):
    """Max pool returning (out, flat per-channel indices) — the mask the
    reference's max_pool2d(return_mask=True) produces (max_pool_with_index
    op) and MaxUnPool2D consumes."""
    ks = _norm_tuple(kernel_size, 2)
    st = _norm_tuple(stride if stride is not None else kernel_size, 2)
    pads = _norm_tuple(padding, 2)

    def _pool(a):
        N, C, H, W = a.shape
        # pad with dtype-min ourselves: patches' implicit padding is ZERO
        # (would beat negative maxima / corrupt indices), and -inf is out
        # too — patch extraction is a conv, and -inf * 0 = NaN
        if pads[0] or pads[1]:
            neg = jnp.finfo(a.dtype).min if \
                jnp.issubdtype(a.dtype, jnp.floating) else \
                jnp.iinfo(a.dtype).min
            a = jnp.pad(a, ((0, 0), (0, 0), (pads[0], pads[0]),
                            (pads[1], pads[1])), constant_values=neg)
        patches = jax.lax.conv_general_dilated_patches(
            a, filter_shape=ks, window_strides=st, padding="VALID")
        oH, oW = patches.shape[2], patches.shape[3]
        # [N, C*kh*kw, oH, oW] -> [N, C, kh*kw, oH, oW]
        patches = patches.reshape(N, C, ks[0] * ks[1], oH, oW)
        local = jnp.argmax(patches, axis=2)          # [N, C, oH, oW]
        out = jnp.max(patches, axis=2)
        oh = jnp.arange(oH)[:, None]
        ow = jnp.arange(oW)[None, :]
        row = oh * st[0] - pads[0] + local // ks[1]
        col = ow * st[1] - pads[1] + local % ks[1]
        idx = (row * W + col).astype(jnp.int32)
        return out, idx

    return apply(_pool, _t(x), name="max_pool2d_with_index")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW", name=None):
    """Inverse of max_pool2d: scatter values at their pooled-from positions
    (reference: nn/functional/pooling.py max_unpool2d / unpool_op)."""
    if data_format != "NCHW":
        raise ValueError("max_unpool2d supports NCHW only")
    ks = _norm_tuple(kernel_size, 2)
    st = _norm_tuple(stride if stride is not None else kernel_size, 2)
    pads = _norm_tuple(padding, 2)

    def _unpool(a, idx):
        N, C, oH, oW = a.shape
        if output_size is not None:
            H, W = output_size[-2], output_size[-1]
        else:
            H = (oH - 1) * st[0] - 2 * pads[0] + ks[0]
            W = (oW - 1) * st[1] - 2 * pads[1] + ks[1]
        flat_vals = a.reshape(N, C, oH * oW)
        flat_idx = idx.reshape(N, C, oH * oW).astype(jnp.int32)
        zeros = jnp.zeros((N, C, H * W), a.dtype)
        out = jax.vmap(jax.vmap(
            lambda z, i, v: z.at[i].set(v)))(zeros, flat_idx, flat_vals)
        return out.reshape(N, C, H, W)

    return apply(_unpool, _t(x), _t(indices), name="max_unpool2d")


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """||x - y + eps||_p along the last dim (reference:
    nn/layer/distance.py PairwiseDistance)."""

    def _pd(a, b):
        d = a - b + epsilon
        if p == float("inf"):
            r = jnp.max(jnp.abs(d), axis=-1, keepdims=keepdim)
        elif p == 0:
            r = jnp.sum((d != 0).astype(a.dtype), axis=-1, keepdims=keepdim)
        else:
            r = jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keepdim) \
                ** (1.0 / p)
        return r

    return apply(_pd, _t(x), _t(y), name="pairwise_distance")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference: nn/functional/loss.py:312,
    matrix_bit_code_functor's SimpleCode default tree).

    Default complete-binary-tree coding over num_classes leaves: for class
    c let v = c + num_classes; at step k the internal node is
    (v >> (k+1)) - 1 and the sigmoid target bit is (v >> k) & 1; steps run
    while v >> (k+1) >= 1. weight: [num_classes-1, D], bias:
    [num_classes-1]. Custom trees pass path_table/path_code
    [N, L] (padded with -1).
    """
    import math as _math
    L = max(1, int(_math.ceil(_math.log2(max(2, num_classes)))) + 1)

    def _hs(x, lab, w, *rest):
        b = rest[0] if rest else None
        lab = lab.astype(jnp.int32).reshape(-1)
        if path_table is not None:
            pt_raw = path_table._data if isinstance(path_table, Tensor) \
                else path_table
            pc_raw = path_code._data if isinstance(path_code, Tensor) \
                else path_code
            pt = jnp.asarray(pt_raw, jnp.int32)
            pc = jnp.asarray(pc_raw, jnp.float32)
            valid = (pt >= 0).astype(jnp.float32)
            idx = jnp.maximum(pt, 0)
            bits = pc
        else:
            v = lab + num_classes
            ks = jnp.arange(L)
            anc = v[:, None] >> (ks[None, :] + 1)          # [N, L]
            valid = (anc >= 1).astype(jnp.float32)
            idx = jnp.maximum(anc - 1, 0)
            bits = ((v[:, None] >> ks[None, :]) & 1).astype(jnp.float32)
        wk = w[idx]                                        # [N, L, D]
        pre = jnp.einsum("nd,nld->nl", x.astype(jnp.float32),
                         wk.astype(jnp.float32))
        if b is not None:
            pre = pre + b[idx]
        # bce-with-logits against the code bit; bit=1 -> sigmoid target 1
        per = jax.nn.softplus(pre) - bits * pre
        loss = jnp.sum(per * valid, axis=-1, keepdims=True)
        return loss

    args = [_t(input), _t(label), _t(weight)] + ([_t(bias)] if bias is not None else [])
    return apply(_hs, *args, name="hsigmoid_loss")


# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------

def _bn_fold_scale_shift(mean, var, gamma, beta, epsilon):
    """Fold BN statistics (+optional affine) into one (scale, shift) pair,
    computed in f32 — shared by batch_norm and fused_conv_bn so the folded
    math can never diverge between the fused and unfused paths."""
    inv = jax.lax.rsqrt(var + epsilon)
    if gamma is not None:
        scale = gamma.astype(jnp.float32) * inv
        shift = beta.astype(jnp.float32) - mean * scale
    else:
        scale = inv
        shift = -mean * inv
    return scale, shift


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None):
    """BatchNorm with running-stat update (reference: nn/functional/norm.py batch_norm).

    Running stats are updated in-place on the buffer tensors in training mode
    (eager). Inside jit traces training stats flow through pure state (the
    jitted trainer hoists buffers into the state pytree).
    """
    x = _t(x)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    # channels-last planner tag: normalize over the NHWC channel axis
    # without leaving the internal layout
    internal_cl = (not channel_last and _layout.is_active()
                   and x._layout == "NHWC")
    if internal_cl:
        channel_last = True
    ch_axis = x.ndim - 1 if channel_last else 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    use_batch_stats = training and not use_global_stats

    # dtype-preserving normalization: statistics and the folded
    # scale/shift compute in f32, the per-element application runs in the
    # INPUT dtype (one multiply + one add, fusable into the producing
    # conv's epilogue). Under the AMP O1 bf16 activation stream this
    # keeps conv->bn->relu chains entirely bf16 — the old blacklisted
    # form round-tripped every conv output through f32, which the
    # ResNet-50 trace showed as ~40 ms/step of pure convert/copy traffic.
    def _bn_apply(a, mean, var, wb):
        scale, shift = _bn_fold_scale_shift(
            mean, var, wb[0] if wb else None, wb[1] if wb else None,
            epsilon)
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        return a * scale.reshape(shape).astype(a.dtype) \
            + shift.reshape(shape).astype(a.dtype)

    def _bn_eval(a, rm, rv, *wb):
        return _bn_apply(a, rm.astype(jnp.float32),
                         rv.astype(jnp.float32), wb)

    if use_batch_stats:
        def _bn_train(a, rm, rv, *wb):
            a32 = a.astype(jnp.float32)
            mean = jnp.mean(a32, axis=reduce_axes)
            var = jnp.var(a32, axis=reduce_axes)
            out = _bn_apply(a, mean, var, wb)
            new_rm = momentum * rm + (1 - momentum) * mean.astype(rm.dtype)
            new_rv = momentum * rv + (1 - momentum) * var.astype(rv.dtype)
            return out, new_rm, new_rv

        args = [x, _t(running_mean), _t(running_var)]
        if weight is not None:
            args += [_t(weight), _t(bias)]
        out, new_rm, new_rv = apply(
            _bn_train, *args, name="batch_norm",
            _cache_token=("bn_train", ch_axis, reduce_axes, momentum,
                          epsilon))
        # in-place update of running stats (buffers); recorded as replayable
        # write events when a static Program is being built, with the eval
        # normalization as the clone(for_test=True) twin
        annotate_test_variant(_bn_eval)
        record_mutation(running_mean, new_rm)
        record_mutation(running_var, new_rv)
        if internal_cl:
            _layout.tag(out)
        return out

    args = [x, _t(running_mean), _t(running_var)]
    if weight is not None:
        args += [_t(weight), _t(bias)]
    out = apply(_bn_eval, *args, name="batch_norm",
                _cache_token=("bn_eval", ch_axis, epsilon))
    if internal_cl:
        _layout.tag(out)
    return out


def fused_conv_bn(x, weight, bias, running_mean, running_var, bn_weight,
                  bn_bias, stride=1, padding=0, dilation=1, groups=1,
                  data_format="NCHW", training=False, momentum=0.9,
                  epsilon=1e-5, activation=None, use_global_stats=None,
                  name=None):
    """Conv2D → BatchNorm → activation as ONE op: the vision fast path's
    epilogue fusion.

    Training: the conv runs on the bf16 activation stream (AMP policy
    resolved here, since the generic dispatch cast must not touch the f32
    EMA buffers), batch statistics accumulate in f32, and the folded
    scale/shift + activation land in the conv's XLA epilogue — one kernel
    region and ONE eager tape node instead of three. Running-stat EMA
    buffers stay f32 under every AMP level (the op is on the AMP
    keep-dtype list, mirroring batch_norm).

    Inference deployments fold the BN entirely into the conv weights
    instead — see paddle_tpu.inference.passes.fold_conv_bn.

    ``activation``: None | "relu" | "relu6".
    """
    if activation not in (None, "relu", "relu6"):
        raise ValueError(f"fused_conv_bn supports relu/relu6, got "
                         f"{activation!r}")
    n = 2
    stride = _norm_tuple(stride, n)
    dilation = _norm_tuple(dilation, n)
    channel_last = data_format == "NHWC"
    x = _t(x)
    internal_cl = (not channel_last and _layout.is_active())
    if internal_cl:
        if x._layout != "NHWC":
            x = _layout.to_channels_last(x)
        channel_last = True
    spec = _conv_specs(n, channel_last)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _norm_tuple(padding, n)
        pad = tuple((pi, pi) for pi in p)
    ch_axis = x.ndim - 1 if channel_last else 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)

    # the conv's AMP cast target, resolved HERE: the op itself is
    # keep-dtype (a blanket input cast would round the f32 EMA buffers
    # through bf16), so the bf16 stream is applied to the conv operands
    # only, inside the op
    from ..core import tensor as _core_tensor
    amp_dt = (_core_tensor._amp_target_hook("conv2d")
              if _core_tensor._amp_target_hook is not None else None)
    act_fn = {None: None, "relu": jax.nn.relu, "relu6": jax.nn.relu6}[activation]
    has_cb = bias is not None
    has_affine = bn_weight is not None

    def _conv_part(a, w, cb):
        if amp_dt is not None:
            td = jnp.dtype(amp_dt)
            a = a.astype(td) if a.dtype != td else a
            w = w.astype(td) if w.dtype != td else w
        out = _run_conv(a, w, stride, pad, None, dilation, spec, groups)
        if cb is not None:
            shape = [1] * out.ndim
            shape[ch_axis] = cb.size
            out = out + cb.reshape(shape).astype(out.dtype)
        return out

    def _bn_part(out, mean, var, gamma, beta):
        scale, shift = _bn_fold_scale_shift(mean, var, gamma, beta, epsilon)
        shape = [1] * out.ndim
        shape[ch_axis] = out.shape[ch_axis]
        y = out * scale.reshape(shape).astype(out.dtype) \
            + shift.reshape(shape).astype(out.dtype)
        return act_fn(y) if act_fn is not None else y

    def _split_rest(rest):
        i = 0
        cb = gamma = beta = None
        if has_cb:
            cb = rest[i]; i += 1
        if has_affine:
            gamma, beta = rest[i], rest[i + 1]
        return cb, gamma, beta

    def _fcb_eval(a, w, rm, rv, *rest):
        cb, gamma, beta = _split_rest(rest)
        out = _conv_part(a, w, cb)
        return _bn_part(out, rm.astype(jnp.float32),
                        rv.astype(jnp.float32), gamma, beta)

    args = [x, _t(weight), _t(running_mean), _t(running_var)]
    if has_cb:
        args.append(_t(bias))
    if has_affine:
        args += [_t(bn_weight), _t(bn_bias)]
    token_tail = (stride, pad, dilation, groups, spec, ch_axis, momentum,
                  epsilon, activation, amp_dt, has_cb, has_affine)

    if training and not use_global_stats:
        def _fcb_train(a, w, rm, rv, *rest):
            cb, gamma, beta = _split_rest(rest)
            out = _conv_part(a, w, cb)
            out32 = out.astype(jnp.float32)
            mean = jnp.mean(out32, axis=reduce_axes)
            var = jnp.var(out32, axis=reduce_axes)
            y = _bn_part(out, mean, var, gamma, beta)
            new_rm = momentum * rm + (1 - momentum) * mean.astype(rm.dtype)
            new_rv = momentum * rv + (1 - momentum) * var.astype(rv.dtype)
            return y, new_rm, new_rv

        out, new_rm, new_rv = apply(
            _fcb_train, *args, name="fused_conv_bn",
            _cache_token=("fcb_train",) + token_tail)
        annotate_test_variant(_fcb_eval)
        record_mutation(running_mean, new_rm)
        record_mutation(running_var, new_rv)
    else:
        out = apply(_fcb_eval, *args, name="fused_conv_bn",
                    _cache_token=("fcb_eval",) + token_tail)
    if internal_cl:
        _layout.tag(out)
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n = len(tuple(normalized_shape))

    def _ln(a, *wb):
        # full f32 internal compute, output in the input dtype: under a
        # bf16 activation stream (AMP O1) the HBM traffic stays half-width
        # while the statistics and the normalization keep f32 accuracy
        # (this is why layer_norm is NOT on the AMP cast lists — the op
        # manages its own precision)
        axes = tuple(range(a.ndim - n, a.ndim))
        af = a.astype(jnp.float32)
        mean = jnp.mean(af, axis=axes, keepdims=True)
        var = jnp.var(af, axis=axes, keepdims=True)
        out = (af - mean) * jax.lax.rsqrt(var + epsilon)
        if wb:
            w = wb[0]
            out = out * w.astype(jnp.float32)
            if len(wb) > 1:
                out = out + wb[1].astype(jnp.float32)
        return out.astype(a.dtype)

    args = [_t(x)]
    if weight is not None:
        args.append(_t(weight))
        if bias is not None:
            args.append(_t(bias))
    return apply(_ln, *args, name="layer_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    def _in(a, *wb):
        axes = tuple(range(2, a.ndim))
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = (a - mean.astype(a.dtype)) * jax.lax.rsqrt(var + eps).astype(a.dtype)
        if wb:
            w, b = wb
            shape = [1] * a.ndim
            shape[1] = a.shape[1]
            out = out * w.reshape(shape) + b.reshape(shape)
        return out
    args = [_t(x)]
    if weight is not None:
        args += [_t(weight), _t(bias)]
    return apply(_in, *args, name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    def _gn(a, *wb):
        N, C = a.shape[0], a.shape[1]
        g = num_groups
        rest = a.shape[2:]
        r = a.reshape((N, g, C // g) + rest).astype(jnp.float32)
        axes = tuple(range(2, r.ndim))
        mean = jnp.mean(r, axis=axes, keepdims=True)
        var = jnp.var(r, axis=axes, keepdims=True)
        out = ((r - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a.shape).astype(a.dtype)
        if wb:
            w, b = wb
            shape = [1, C] + [1] * (a.ndim - 2)
            out = out * w.reshape(shape) + b.reshape(shape)
        return out
    args = [_t(x)]
    if weight is not None:
        args += [_t(weight), _t(bias)]
    return apply(_gn, *args, name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def _lrn(a):
        sq = jnp.square(a)
        half = size // 2
        pads = [(0, 0)] * a.ndim
        pads[1] = (half, size - half - 1)
        padded = jnp.pad(sq, pads)
        window = [1] * a.ndim
        window[1] = size
        strides = [1] * a.ndim
        s = jax.lax.reduce_window(padded, 0.0, jax.lax.add, tuple(window),
                                  tuple(strides), [(0, 0)] * a.ndim)
        return a / jnp.power(k + alpha * s / size, beta)
    return apply(_lrn, _t(x), name="local_response_norm")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def _nm(a):
        nrm = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(nrm, epsilon)
    return apply(_nm, _t(x), name="normalize")


# ---------------------------------------------------------------------------
# Dropout
# ---------------------------------------------------------------------------

def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None, rng_name=None):
    if not training or p == 0.0:
        return _t(x)
    if p >= 1.0:
        x = _t(x)
        out = apply(lambda a: jnp.zeros_like(a), x, name="dropout")
        if mode == "upscale_in_train":
            annotate_test_variant(lambda a: a)
        else:           # downscale_in_infer at eval: x*(1-p) == 0 for p>=1
            annotate_test_variant(lambda a: jnp.zeros_like(a))
        return out
    key = make_rng(rng_name)

    def _do(a):
        if axis is None and mode == "upscale_in_train" \
                and a.size >= 65536 and jax.default_backend() == "tpu":
            # single-pass Pallas kernel: in-kernel counter-based mask,
            # regenerated in the backward — one HBM read + one write
            # instead of XLA's bits/mask/product round-trips
            from ..ops.pallas.dropout import fused_dropout
            return fused_dropout(a, p, key)
        if axis is None:
            shape = a.shape
        else:
            axes = (axis,) if isinstance(axis, int) else tuple(axis)
            shape = tuple(a.shape[i] if i in axes else 1 for i in range(a.ndim))
        # integer threshold on raw 16-bit random words instead of
        # bernoulli's uniform-float path: half the RNG bytes and no
        # int->float convert chain, at a keep-probability granularity of
        # 2^-16 (irrelevant next to bf16 activation noise)
        bits = jax.random.bits(key, shape, dtype=jnp.uint16)
        keep = bits >= jnp.uint16(min(round(p * 65536.0), 65535))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    out = apply(_do, _t(x), name="dropout")
    # clone(for_test=True) twin: identity (upscale_in_train) / (1-p) scale
    if mode == "upscale_in_train":
        annotate_test_variant(lambda a: a)
    else:
        annotate_test_variant(lambda a: (a * (1.0 - p)).astype(a.dtype))
    return out


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ch_axes = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p, axis=list(ch_axes), training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ch_axes = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p, axis=list(ch_axes), training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return _t(x)
    key = make_rng()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def _ad(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p ** 2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(a.dtype)

    out = apply(_ad, _t(x), name="alpha_dropout")
    annotate_test_variant(lambda a: a)   # eval: identity
    return out


# ---------------------------------------------------------------------------
# Embedding
# ---------------------------------------------------------------------------

def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def _emb(ids, w):
        out = jnp.take(w, ids.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return apply(_emb, _t(x), _t(weight), name="embedding")


def one_hot(x, num_classes, name=None):
    return apply(lambda a: jax.nn.one_hot(a.astype(jnp.int32), num_classes),
                 _t(x), name="one_hot")


# ---------------------------------------------------------------------------
# Losses
# ---------------------------------------------------------------------------

def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    """Softmax cross entropy (reference: nn/functional/loss.py cross_entropy).

    Above ``FLAGS_chunked_ce_threshold`` vocab entries (last-axis softmax,
    no label smoothing) the loss streams over vocab chunks with an online
    f32 logsumexp instead of materializing full-vocab f32 log-probs — see
    nn/chunked_ce.py. Same semantics (ignore_index / soft_label / weights /
    reduction), custom-VJP backward."""
    w = _t(weight) if weight is not None else None
    inp_t = _t(input)
    n_classes = inp_t.shape[axis]
    from . import chunked_ce as _cce
    if (use_softmax and not label_smoothing
            and axis in (-1, inp_t.ndim - 1)
            and _cce.enabled_for(n_classes)):
        chunk = _cce.chunk_size_for(n_classes)

        def _ce_chunked(logits, lab, *maybe_w):
            if soft_label:
                loss = _cce.soft_nll(logits, lab.astype(jnp.float32),
                                     chunk=chunk)
                valid = jnp.ones_like(loss, jnp.float32)
            else:
                ids = lab.astype(jnp.int32)
                if ids.ndim == logits.ndim:
                    ids = jnp.squeeze(ids, -1)
                valid = (ids != ignore_index).astype(jnp.float32)
                safe_ids = jnp.where(ids == ignore_index, 0, ids)
                loss = _cce.hard_nll(logits, safe_ids, chunk=chunk) * valid
                if maybe_w:
                    sample_w = jnp.take(maybe_w[0], safe_ids, axis=0) * valid
                    loss = loss * sample_w
                    valid = sample_w
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(valid), 1e-12)
                return jnp.sum(loss) / denom
            return _reduce(loss, reduction)

        args = [inp_t, _t(label)] + ([w] if w is not None else [])
        # hard_nll resolves its Pallas-vs-XLA dispatch at trace time, so
        # the outcome must ride the cache token: a kill-switch flip
        # (FLAGS_pallas_ce / FLAGS_pallas_interpret) would otherwise keep
        # serving the stale cached trace for already-seen signatures
        from ..ops import pallas as pallas_ops
        ce_kernel = (not soft_label
                     and pallas_ops.kernel_enabled("chunked_ce",
                                                   note=False))
        return apply(_ce_chunked, *args, name="cross_entropy",
                     _cache_token=("ce_chunked", reduction, ignore_index,
                                   bool(soft_label), chunk, ce_kernel))

    def _ce(logits, lab, *maybe_w):
        if use_softmax:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
        else:
            logp = jnp.log(jnp.maximum(logits.astype(jnp.float32), 1e-30))
        if soft_label:
            target = lab.astype(jnp.float32)
            if label_smoothing:
                n = logits.shape[axis]
                target = target * (1 - label_smoothing) + label_smoothing / n
            loss = -jnp.sum(target * logp, axis=axis)
            valid = jnp.ones_like(loss, jnp.float32)
        else:
            ids = lab.astype(jnp.int32)
            if ids.ndim == logp.ndim:
                ids = jnp.squeeze(ids, axis)
            valid = (ids != ignore_index).astype(jnp.float32)
            safe_ids = jnp.where(ids == ignore_index, 0, ids)
            if label_smoothing:
                n = logits.shape[axis]
                nll = -jnp.take_along_axis(logp, safe_ids[..., None], axis=axis)[..., 0]
                smooth = -jnp.mean(logp, axis=axis)
                loss = (1 - label_smoothing) * nll + label_smoothing * smooth
            else:
                loss = -jnp.take_along_axis(logp, safe_ids[..., None], axis=axis)[..., 0]
            loss = loss * valid
            if maybe_w:
                sample_w = jnp.take(maybe_w[0], safe_ids, axis=0) * valid
                loss = loss * sample_w
                # weighted mean divides by the gathered weight sum
                # (reference: nn/functional/loss.py ret = out_sum / weight_sum)
                valid = sample_w
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid), 1e-12)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    args = [_t(input), _t(label)] + ([w] if w is not None else [])
    return apply(_ce, *args, name="cross_entropy",
                 _cache_token=("ce", reduction, axis, ignore_index,
                               bool(soft_label), bool(use_softmax),
                               float(label_smoothing)))


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False,
                               axis=-1, name=None):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    # paddle keeps the label-dim
    loss = loss.unsqueeze(axis)
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def _bce(p, y, *mw):
        p32 = jnp.clip(p.astype(jnp.float32), 1e-7, 1 - 1e-7)
        loss = -(y * jnp.log(p32) + (1 - y) * jnp.log1p(-p32))
        if mw:
            loss = loss * mw[0]
        return _reduce(loss, reduction)
    args = [_t(input), _t(label)] + ([_t(weight)] if weight is not None else [])
    return apply(_bce, *args, name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def _bcel(z, y, *extra):
        z32 = z.astype(jnp.float32)
        y32 = y.astype(jnp.float32)
        i = 0
        w = pw = None
        if weight is not None:
            w = extra[i]; i += 1
        if pos_weight is not None:
            pw = extra[i]; i += 1
        log_sig = jax.nn.log_sigmoid(z32)
        log_one_minus = jax.nn.log_sigmoid(-z32)
        if pw is not None:
            loss = -(pw * y32 * log_sig + (1 - y32) * log_one_minus)
        else:
            loss = -(y32 * log_sig + (1 - y32) * log_one_minus)
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    args = [_t(logit), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    if pos_weight is not None:
        args.append(_t(pos_weight))
    return apply(_bcel, *args, name="bce_with_logits")


def mse_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.square(a - b), reduction),
                 _t(input), _t(label), name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                 _t(input), _t(label), name="l1_loss")


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def _nll(logp, y, *mw):
        ids = y.astype(jnp.int32)
        valid = (ids != ignore_index).astype(jnp.float32)
        safe = jnp.where(ids == ignore_index, 0, ids)
        loss = -jnp.take_along_axis(logp, safe[..., None], axis=1)[..., 0] if logp.ndim == 2 \
            else -jnp.take_along_axis(logp, safe[:, None], axis=1)[:, 0]
        if mw:
            wv = jnp.take(mw[0], safe, axis=0)
            loss = loss * wv
            valid = valid * wv
        loss = loss * (ids != ignore_index)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1e-12)
        return _reduce(loss, reduction)
    args = [_t(input), _t(label)] + ([_t(weight)] if weight is not None else [])
    return apply(_nll, *args, name="nll_loss")


def kl_div(input, label, reduction="mean", name=None):
    def _kl(logp, y):
        loss = y * (jnp.log(jnp.maximum(y, 1e-30)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return apply(_kl, _t(input), _t(label), name="kl_div")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def _sl1(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)
    return apply(_sl1, _t(input), _t(label), name="smooth_l1_loss")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def _mrl(a, b, y):
        loss = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce(loss, reduction)
    return apply(_mrl, _t(input), _t(other), _t(label), name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def _hel(a, y):
        loss = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)
    return apply(_hel, _t(input), _t(label), name="hinge_embedding_loss")


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean", name=None):
    def _cel(a, b, y):
        cos = jnp.sum(a * b, -1) / (jnp.linalg.norm(a, axis=-1) *
                                    jnp.linalg.norm(b, axis=-1) + 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply(_cel, _t(input1), _t(input2), _t(label), name="cosine_embedding_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def _sfl(z, y, *mn):
        p = jax.nn.sigmoid(z)
        ce = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if mn:
            loss = loss / mn[0]
        return _reduce(loss, reduction)
    args = [_t(logit), _t(label)] + ([_t(normalizer)] if normalizer is not None else [])
    return apply(_sfl, *args, name="sigmoid_focal_loss")


def square_error_cost(input, label):
    return apply(lambda a, b: jnp.square(a - b), _t(input), _t(label),
                 name="square_error_cost")


def log_loss(input, label, epsilon=1e-4, name=None):
    def _ll(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)
    return apply(_ll, _t(input), _t(label), name="log_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def _np(a, p, y):
        sim = jnp.matmul(a, p.T, precision=matmul_precision())
        y2 = (y[:, None] == y[None, :]).astype(jnp.float32)
        y2 = y2 / jnp.sum(y2, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        xent = -jnp.mean(jnp.sum(y2 * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1)) + jnp.mean(jnp.sum(p * p, 1))) * 0.25
        return xent + reg
    return apply(_np, _t(anchor), _t(positive), _t(labels), name="npair_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def _tml(a, pos, neg):
        d_ap = jnp.sum(jnp.abs(a - pos) ** p, -1) ** (1 / p)
        d_an = jnp.sum(jnp.abs(a - neg) ** p, -1) ** (1 / p)
        if swap:
            d_pn = jnp.sum(jnp.abs(pos - neg) ** p, -1) ** (1 / p)
            d_an = jnp.minimum(d_an, d_pn)
        loss = jnp.maximum(d_ap - d_an + margin, 0.0)
        return _reduce(loss, reduction)
    return apply(_tml, _t(input), _t(positive), _t(negative), name="triplet_margin_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss via optax (reference: operators warpctc)."""
    import optax
    def _ctc(lp, lab, il, ll):
        # optax expects [B, T, V] logits and paddings
        logits = jnp.transpose(lp, (1, 0, 2)) if lp.ndim == 3 else lp
        B, T, V = logits.shape
        t_idx = jnp.arange(T)[None, :]
        logit_pad = (t_idx >= il[:, None]).astype(jnp.float32)
        L = lab.shape[1]
        l_idx = jnp.arange(L)[None, :]
        label_pad = (l_idx >= ll[:, None]).astype(jnp.float32)
        loss = optax.ctc_loss(logits, logit_pad, lab.astype(jnp.int32), label_pad,
                              blank_id=blank)
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(ll.astype(jnp.float32), 1.0))
        return _reduce(loss, reduction)
    return apply(_ctc, _t(log_probs), _t(labels), _t(input_lengths),
                 _t(label_lengths), name="ctc_loss")


# ---------------------------------------------------------------------------
# Shape ops
# ---------------------------------------------------------------------------

def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = _t(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]

    def _pad(a):
        if len(pad) == 2 * a.ndim:
            pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(a.ndim)]
        else:
            # paddle convention: pad applies to last len(pad)//2 spatial dims,
            # ordered (left, right, top, bottom, front, back) starting at the
            # innermost spatial axis; NC* dims get zero.
            n = len(pad) // 2
            pairs = [(0, 0)] * a.ndim
            channel_last = data_format in ("NHWC", "NLC", "NDHWC")
            spatial_axes = list(range(1, a.ndim - 1)) if channel_last \
                else list(range(2, a.ndim))
            for i in range(n):
                ax = spatial_axes[len(spatial_axes) - 1 - i]
                pairs[ax] = (pad[2 * i], pad[2 * i + 1])
        if mode == "constant":
            return jnp.pad(a, pairs, constant_values=value)
        jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
        return jnp.pad(a, pairs, mode=jmode)

    return apply(_pad, x, name="pad")


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW", name=None):
    x = _t(x)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    nd = x.ndim - 2
    in_spatial = x.shape[1:-1] if channel_last else x.shape[2:]
    if size is not None:
        if isinstance(size, Tensor):
            size = [int(s) for s in np.asarray(size.data)]
        out_spatial = tuple(int(s) for s in (size if isinstance(size, (list, tuple)) else [size]))
    else:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * nd
        out_spatial = tuple(int(i * s) for i, s in zip(in_spatial, scale_factor))

    method = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
              "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def _interp(a):
        if channel_last:
            shape = (a.shape[0],) + out_spatial + (a.shape[-1],)
        else:
            shape = a.shape[:2] + out_spatial
        if method == "nearest" or not align_corners:
            return jax.image.resize(a, shape, method=method).astype(a.dtype)
        # align_corners linear: explicit gather-based interp
        out = a
        spatial0 = 1 if channel_last else 2
        for i, osz in enumerate(out_spatial):
            ax = spatial0 + i
            isz = out.shape[ax]
            if osz == isz:
                continue
            if osz == 1:
                idx = jnp.zeros((1,), jnp.float32)
            else:
                idx = jnp.arange(osz, dtype=jnp.float32) * (isz - 1) / (osz - 1)
            lo = jnp.floor(idx).astype(jnp.int32)
            hi = jnp.minimum(lo + 1, isz - 1)
            w = (idx - lo).astype(a.dtype)
            lo_vals = jnp.take(out, lo, axis=ax)
            hi_vals = jnp.take(out, hi, axis=ax)
            bshape = [1] * out.ndim
            bshape[ax] = osz
            w = w.reshape(bshape)
            out = lo_vals * (1 - w) + hi_vals * w
        return out

    return apply(_interp, x, name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor
    def _ps(a):
        N, C, H, W = a.shape
        out = a.reshape(N, C // (r * r), r, r, H, W)
        out = jnp.transpose(out, (0, 1, 4, 2, 5, 3))
        return out.reshape(N, C // (r * r), H * r, W * r)
    return apply(_ps, _t(x), name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor
    def _pu(a):
        N, C, H, W = a.shape
        out = a.reshape(N, C, H // r, r, W // r, r)
        out = jnp.transpose(out, (0, 1, 3, 5, 2, 4))
        return out.reshape(N, C * r * r, H // r, W // r)
    return apply(_pu, _t(x), name="pixel_unshuffle")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    ks = _norm_tuple(kernel_sizes, 2)
    st = _norm_tuple(strides, 2)
    pd = _norm_tuple(paddings, 2)
    dl = _norm_tuple(dilations, 2)

    def _unfold(a):
        N, C, H, W = a.shape
        patches = jax.lax.conv_general_dilated_patches(
            a, filter_shape=ks, window_strides=st,
            padding=[(pd[0], pd[0]), (pd[1], pd[1])], rhs_dilation=dl,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # patches: [N, C*kh*kw, oh, ow]
        return patches.reshape(N, patches.shape[1], -1)

    return apply(_unfold, _t(x), name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    os_ = _norm_tuple(output_sizes, 2)
    ks = _norm_tuple(kernel_sizes, 2)
    st = _norm_tuple(strides, 2)
    pd = _norm_tuple(paddings, 2)

    def _fold(a):
        N, CKK, L = a.shape
        C = CKK // (ks[0] * ks[1])
        oh = (os_[0] + 2 * pd[0] - ks[0]) // st[0] + 1
        ow = (os_[1] + 2 * pd[1] - ks[1]) // st[1] + 1
        cols = a.reshape(N, C, ks[0], ks[1], oh, ow)
        out = jnp.zeros((N, C, os_[0] + 2 * pd[0], os_[1] + 2 * pd[1]), a.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                out = out.at[:, :, i:i + oh * st[0]:st[0], j:j + ow * st[1]:st[1]].add(
                    cols[:, :, i, j])
        return out[:, :, pd[0]:os_[0] + pd[0], pd[1]:os_[1] + pd[1]]

    return apply(_fold, _t(x), name="fold")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    if isinstance(out_shape, Tensor):
        out_shape = [int(s) for s in np.asarray(out_shape.data)]

    def _ag(th):
        N, _, H, W = out_shape[0], out_shape[1], out_shape[2], out_shape[3]
        if align_corners:
            xs = jnp.linspace(-1, 1, W)
            ys = jnp.linspace(-1, 1, H)
        else:
            xs = (jnp.arange(W) * 2 + 1) / W - 1
            ys = (jnp.arange(H) * 2 + 1) / H - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # [HW, 3]
        out = jnp.einsum("nij,kj->nki", th, base)  # [N, HW, 2]
        return out.reshape(N, H, W, 2)

    return apply(_ag, _t(theta), name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    def _gs(a, g):
        N, C, H, W = a.shape
        gx = g[..., 0]
        gy = g[..., 1]
        if align_corners:
            fx = (gx + 1) * (W - 1) / 2
            fy = (gy + 1) * (H - 1) / 2
        else:
            fx = ((gx + 1) * W - 1) / 2
            fy = ((gy + 1) * H - 1) / 2

        def sample(ix, iy):
            ixc = jnp.clip(ix, 0, W - 1)
            iyc = jnp.clip(iy, 0, H - 1)
            v = a[jnp.arange(N)[:, None, None], :, iyc, ixc]  # [N,h,w,C]
            if padding_mode == "zeros":
                valid = ((ix >= 0) & (ix < W) & (iy >= 0) & (iy < H))[..., None]
                v = jnp.where(valid, v, 0.0)
            return v

        if mode == "nearest":
            out = sample(jnp.round(fx).astype(jnp.int32), jnp.round(fy).astype(jnp.int32))
        else:
            x0 = jnp.floor(fx).astype(jnp.int32)
            y0 = jnp.floor(fy).astype(jnp.int32)
            x1, y1 = x0 + 1, y0 + 1
            wa = ((x1 - fx) * (y1 - fy))[..., None]
            wb = ((x1 - fx) * (fy - y0))[..., None]
            wc = ((fx - x0) * (y1 - fy))[..., None]
            wd = ((fx - x0) * (fy - y0))[..., None]
            out = (sample(x0, y0) * wa + sample(x0, y1) * wb +
                   sample(x1, y0) * wc + sample(x1, y1) * wd)
        return jnp.transpose(out, (0, 3, 1, 2)).astype(a.dtype)

    return apply(_gs, _t(x), _t(grid), name="grid_sample")


# ---------------------------------------------------------------------------
# Misc
# ---------------------------------------------------------------------------

def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def _cs(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
        return num / jnp.maximum(den, eps)
    return apply(_cs, _t(x1), _t(x2), name="cosine_similarity")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def _ls(y, *pd):
        n = y.shape[-1]
        if pd:
            return (1 - epsilon) * y + epsilon * pd[0]
        return (1 - epsilon) * y + epsilon / n
    args = [_t(label)] + ([_t(prior_dist)] if prior_dist is not None else [])
    return apply(_ls, *args, name="label_smooth")


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    x = _t(x)
    ml = maxlen if maxlen is not None else int(np.asarray(x.data).max())
    d = dtypes.convert_dtype(dtype)
    return apply(lambda a: (jnp.arange(ml)[None, :] < a[..., None]).astype(d),
                 x, name="sequence_mask")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def _ts(a):
        NT, C, H, W = a.shape
        N = NT // seg_num
        r = a.reshape(N, seg_num, C, H, W)
        fold_c = int(C * shift_ratio)
        left = jnp.concatenate([r[:, 1:, :fold_c], jnp.zeros_like(r[:, :1, :fold_c])], 1)
        right = jnp.concatenate([jnp.zeros_like(r[:, :1, fold_c:2 * fold_c]),
                                 r[:, :-1, fold_c:2 * fold_c]], 1)
        rest = r[:, :, 2 * fold_c:]
        out = jnp.concatenate([left, right, rest], axis=2)
        return out.reshape(NT, C, H, W)
    return apply(_ts, _t(x), name="temporal_shift")


def class_center_sample(label, num_classes, num_samples, group=None):
    """Partial-FC class-center sampling (reference:
    nn/functional/common.py:1586, class_center_sample_op.cu): keep every
    positive class in `label`, top up with uniformly sampled negatives to
    `num_samples`, and remap labels into the sampled index space (labels
    whose class was not sampled keep... all positives are always sampled,
    so every label remaps). Returns (remapped_label, sampled_class_index).

    Host-side op (eager data-prep, like the reference's usage before the
    sharded margin-softmax matmul); RNG comes from the global generator.
    """
    import numpy as np

    from ..core.random import default_generator

    arr = np.asarray(label._data if isinstance(label, Tensor) else label)
    if arr.ndim != 1:
        raise ValueError("class_center_sample expects 1-D labels")
    if num_samples > num_classes:
        raise ValueError(f"num_samples {num_samples} > num_classes "
                         f"{num_classes}")
    positives = np.unique(arr)
    if len(positives) >= num_samples:
        sampled = positives
    else:
        seed_key = default_generator().next_key()
        import jax
        seed = int(jax.random.randint(seed_key, (), 0, 2**31 - 1))
        rng = np.random.default_rng(seed)
        mask = np.ones(num_classes, bool)
        mask[positives] = False
        negatives = np.nonzero(mask)[0]
        extra = rng.choice(negatives, num_samples - len(positives),
                           replace=False)
        sampled = np.sort(np.concatenate([positives, extra]))
    remap = np.full(num_classes, -1, np.int64)
    remap[sampled] = np.arange(len(sampled))
    out_label = remap[arr]
    return (Tensor(jnp.asarray(out_label, jnp.int32)),
            Tensor(jnp.asarray(sampled, jnp.int32)))


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """Fused attention entry (reference: operators/fused/fused_attention_op.cu).

    Dispatches to the Pallas flash-attention kernel on TPU for supported
    shapes; falls back to the XLA composition otherwise. Layout: [B, S, H, D].
    """
    from ..ops.attention import scaled_dot_product_attention as _sdpa
    args = [_t(query), _t(key), _t(value)]
    mask = _t(attn_mask) if attn_mask is not None else None
    return _sdpa(args[0], args[1], args[2], mask, dropout_p, is_causal, training)


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention (reference: nn/functional/sparse_attention.py).

    Implemented as dense attention with a mask built from the CSR pattern —
    on TPU the MXU prefers dense tiles; true block-sparsity comes from the
    Pallas flash kernel's block skipping.
    """
    def _sa(q, k, v, offs, cols):
        B, H, S, D = q.shape
        scale = 1.0 / math.sqrt(D)
        scores = jnp.einsum("bhsd,bhtd->bhst", q, k,
                            precision=matmul_precision()) * scale
        # build dense mask from CSR (host-side shapes, device gather)
        row_ids = jnp.repeat(jnp.arange(S), jnp.diff(offs[0, 0]), total_repeat_length=cols.shape[-1])
        mask = jnp.zeros((S, S), bool).at[row_ids, cols[0, 0]].set(True)
        scores = jnp.where(mask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhst,bhtd->bhsd", probs, v,
                          precision=matmul_precision())
    return apply(_sa, _t(query), _t(key), _t(value), _t(sparse_csr_offset),
                 _t(sparse_csr_columns), name="sparse_attention")


def bilinear(x1, x2, weight, bias=None, name=None):
    """out[b, o] = x1[b, i] W[o, i, j] x2[b, j] + bias (reference:
    nn/functional/common.py bilinear over bilinear_tensor_product_op)."""

    def _bl(a, b, w, *bb):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        return out + bb[0] if bb else out

    args = [_t(x1), _t(x2), _t(weight)] + ([_t(bias)]
                                           if bias is not None else [])
    return apply(_bl, *args, name="bilinear")


def thresholded_relu(x, threshold=1.0, name=None):
    """x if x > threshold else 0 (reference: activation.py
    thresholded_relu)."""
    return apply(lambda a: jnp.where(a > threshold, a, 0.0).astype(a.dtype),
                 _t(x), name="thresholded_relu")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """ArcFace/CosFace-style margin softmax CE (reference:
    operators/margin_cross_entropy_op.cu): the target logit cos(theta) is
    replaced by cos(m1*theta + m2) - m3, everything scaled by s."""

    def _mce(lg, lab):
        lg32 = lg.astype(jnp.float32)
        ids = lab.astype(jnp.int32).reshape(-1)
        tgt = jnp.take_along_axis(lg32, ids[:, None], axis=-1)[:, 0]
        theta = jnp.arccos(jnp.clip(tgt, -1.0, 1.0))
        tgt_m = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(ids, lg32.shape[-1], dtype=lg32.dtype)
        adj = lg32 * (1 - onehot) + tgt_m[:, None] * onehot
        adj = adj * scale
        lse = jax.nn.logsumexp(adj, axis=-1)
        per = lse - jnp.take_along_axis(adj, ids[:, None], axis=-1)[:, 0]
        sm = jax.nn.softmax(adj, axis=-1)
        if reduction == "mean":
            loss = jnp.mean(per)
        elif reduction == "sum":
            loss = jnp.sum(per)
        else:
            loss = per
        return (loss, sm)

    loss_sm = apply(_mce, _t(logits), _t(label), name="margin_cross_entropy")
    if return_softmax:
        return loss_sm
    return loss_sm[0]


def _make_inplace(fn_name):
    def inplace(x, *args, **kwargs):
        from ..tensor.tail import _rebind
        out = globals()[fn_name](x, *args, **kwargs)
        return _rebind(_t(x), out)
    inplace.__name__ = fn_name + "_"
    inplace.__doc__ = (f"Inplace variant of :func:`{fn_name}` "
                       "(rebinds the tensor's buffer).")
    return inplace


relu_ = _make_inplace("relu")
elu_ = _make_inplace("elu")
softmax_ = _make_inplace("softmax")


from ..tensor.tail import diag_embed  # noqa: E402,F401
