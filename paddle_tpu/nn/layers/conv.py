"""Convolution layers (reference: python/paddle/nn/layer/conv.py).

Kernel layout follows the reference: [out_c, in_c/groups, *spatial]; the
functional layer maps onto lax.conv_general_dilated which XLA tiles onto
the MXU.
"""

from __future__ import annotations

import numpy as np

from .. import functional as F
from ..initializer import Constant, KaimingUniform, Uniform, XavierUniform
from ..layer import Layer

__all__ = [
    "Conv1D", "Conv2D", "Conv3D",
    "Conv1DTranspose", "Conv2DTranspose", "Conv3DTranspose",
    "fused_conv_bn_act",
]


def _ntuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(i) for i in v)


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, n, transpose,
                 stride=1, padding=0, output_padding=0, dilation=1, groups=1,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format=None):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _ntuple(kernel_size, n)
        self._stride = _ntuple(stride, n)
        self._padding = padding
        self._output_padding = output_padding
        self._dilation = _ntuple(dilation, n)
        self._groups = groups
        self._data_format = data_format
        self._n = n
        self._transpose = transpose

        if transpose:
            filter_shape = (in_channels, out_channels // groups) + self._kernel_size
        else:
            filter_shape = (out_channels, in_channels // groups) + self._kernel_size
        fan_in = in_channels * int(np.prod(self._kernel_size)) // groups
        bound = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            filter_shape, attr=weight_attr,
            default_initializer=Uniform(-bound, bound))
        self.bias = self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True,
            default_initializer=Uniform(-bound, bound))

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}")


def fused_conv_bn_act(conv, bn, x, activation=None):
    """Run a (Conv2D, BatchNorm2D) layer pair (+optional relu/relu6) as ONE
    fused op — the vision models' conv→BN→act fast path.

    Falls back to the plain three-op composition when the fusion flag is
    off or when either layer is not the stock class (quant-wrapped convs,
    BN already folded to Identity by the inference pass, ...), so callers
    can use it unconditionally. Parameter/buffer naming is untouched —
    this reads the layers' existing state, it does not restructure them.
    """
    from ...core.flags import get_flag
    from .norm import SyncBatchNorm, _BatchNormBase

    if get_flag("fused_conv_bn") and type(conv) is Conv2D \
            and isinstance(bn, _BatchNormBase) \
            and not isinstance(bn, SyncBatchNorm):
        return F.fused_conv_bn(
            x, conv.weight, conv.bias, bn._mean, bn._variance, bn.weight,
            bn.bias, stride=conv._stride, padding=conv._padding,
            dilation=conv._dilation, groups=conv._groups,
            data_format=conv._data_format, training=bn.training,
            momentum=bn._momentum, epsilon=bn._epsilon,
            activation=activation, use_global_stats=bn._use_global_stats)
    out = bn(conv(x))
    if activation == "relu":
        out = F.relu(out)
    elif activation == "relu6":
        out = F.relu6(out)
    return out


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, False,
                         stride, padding, 0, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, False,
                         stride, padding, 0, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, False,
                         stride, padding, 0, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, True,
                         stride, padding, output_padding, dilation, groups,
                         "zeros", weight_attr, bias_attr, data_format)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, True,
                         stride, padding, output_padding, dilation, groups,
                         "zeros", weight_attr, bias_attr, data_format)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, True,
                         stride, padding, output_padding, dilation, groups,
                         "zeros", weight_attr, bias_attr, data_format)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)
