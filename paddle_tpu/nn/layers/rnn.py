"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py — cudnn LSTM/GRU).

TPU-native design: cells are jnp compositions; the sequence loop uses
jax.lax.scan inside a single traced op so XLA compiles one fused loop instead
of per-step dispatch (the cudnn-RNN analogue).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, apply
from .. import functional as F
from ..initializer import Uniform
from ..layer import Layer, LayerList

__all__ = ["RNNCellBase", "RNNBase",
           "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN",
           "SimpleRNN", "LSTM", "GRU"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        from ...tensor.creation import full
        B = batch_ref.shape[batch_dim_idx]
        states_shapes = self.state_shape
        if isinstance(states_shapes, (list, tuple)) and \
                isinstance(states_shapes[0], (list, tuple)):
            return tuple(full((B,) + tuple(s), init_value, dtype)
                         for s in states_shapes)
        return full((B,) + tuple(states_shapes), init_value, dtype)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter((hidden_size, input_size),
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter((hidden_size, hidden_size),
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter((hidden_size,), bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter((hidden_size,), bias_hh_attr,
                                             is_bias=True, default_initializer=init)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def _cell(x, h, wi, wh, bi, bh):
            out = act(x @ wi.T + bi + h @ wh.T + bh)
            return out

        h = apply(_cell, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh, name="simple_rnn_cell")
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter((4 * hidden_size, input_size),
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter((4 * hidden_size, hidden_size),
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter((4 * hidden_size,), bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter((4 * hidden_size,), bias_hh_attr,
                                             is_bias=True, default_initializer=init)
        self.input_size = input_size
        self.hidden_size = hidden_size

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states

        def _cell(x, h, c, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            new_c = f * c + i * g
            new_h = o * jnp.tanh(new_c)
            return new_h, new_c

        new_h, new_c = apply(_cell, inputs, h, c, self.weight_ih, self.weight_hh,
                             self.bias_ih, self.bias_hh, name="lstm_cell")
        return new_h, (new_h, new_c)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter((3 * hidden_size, input_size),
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter((3 * hidden_size, hidden_size),
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter((3 * hidden_size,), bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter((3 * hidden_size,), bias_hh_attr,
                                             is_bias=True, default_initializer=init)
        self.input_size = input_size
        self.hidden_size = hidden_size

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def _cell(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (1 - z) * c + z * h

        h = apply(_cell, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh, name="gru_cell")
        return h, h


class RNN(Layer):
    """Run a cell over a sequence via lax.scan (single fused XLA loop)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            batch_ref = inputs
            idx = 0 if self.time_major else 0
            B = inputs.shape[1] if self.time_major else inputs.shape[0]
            from ...tensor.creation import zeros
            ss = self.cell.state_shape
            if isinstance(ss[0], (tuple, list)):
                initial_states = tuple(zeros((B,) + tuple(s)) for s in ss)
            else:
                initial_states = zeros((B,) + tuple(ss))

        cell = self.cell
        time_major = self.time_major
        is_reverse = self.is_reverse
        is_lstm = isinstance(cell, LSTMCell)
        is_gru = isinstance(cell, GRUCell)

        params = [cell.weight_ih, cell.weight_hh, cell.bias_ih, cell.bias_hh]
        states_list = list(initial_states) if isinstance(initial_states, (tuple, list)) \
            else [initial_states]

        def _rnn(x, *arrs):
            n_states = len(states_list)
            states0 = arrs[:n_states]
            wi, wh, bi, bh = arrs[n_states:]
            seq = x if time_major else jnp.swapaxes(x, 0, 1)
            if is_reverse:
                seq = jnp.flip(seq, 0)

            def step(carry, xt):
                if is_lstm:
                    h, c = carry
                    gates = xt @ wi.T + bi + h @ wh.T + bh
                    i, f, g, o = jnp.split(gates, 4, axis=-1)
                    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
                    g = jnp.tanh(g)
                    new_c = f * c + i * g
                    new_h = o * jnp.tanh(new_c)
                    return (new_h, new_c), new_h
                if is_gru:
                    h = carry[0]
                    gi = xt @ wi.T + bi
                    gh = h @ wh.T + bh
                    ir, iz, ic = jnp.split(gi, 3, axis=-1)
                    hr, hz, hc = jnp.split(gh, 3, axis=-1)
                    r = jax.nn.sigmoid(ir + hr)
                    z = jax.nn.sigmoid(iz + hz)
                    cand = jnp.tanh(ic + r * hc)
                    new_h = (1 - z) * cand + z * h
                    return (new_h,), new_h
                h = carry[0]
                new_h = jnp.tanh(xt @ wi.T + bi + h @ wh.T + bh)
                return (new_h,), new_h

            final, outs = jax.lax.scan(step, tuple(states0), seq)
            if is_reverse:
                outs = jnp.flip(outs, 0)
            if not time_major:
                outs = jnp.swapaxes(outs, 0, 1)
            return (outs,) + tuple(final)

        results = apply(_rnn, inputs, *states_list, *params, name="rnn_scan")
        outputs = results[0]
        final_states = results[1:]
        if is_lstm:
            return outputs, tuple(final_states)
        return outputs, final_states[0]


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        states_fw = states_bw = None
        if initial_states is not None:
            states_fw, states_bw = initial_states
        out_fw, st_fw = self.rnn_fw(inputs, states_fw)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw)
        from ...tensor.manipulation import concat
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None):
        super().__init__()
        self.mode = mode
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        self.hidden_size = hidden_size
        bidirect = 2 if direction in ("bidirect", "bidirectional") else 1
        self.num_directions = bidirect

        cell_cls = {"LSTM": LSTMCell, "GRU": GRUCell, "RNN_TANH": SimpleRNNCell}[mode]
        kwargs = dict(weight_ih_attr=weight_ih_attr, weight_hh_attr=weight_hh_attr,
                      bias_ih_attr=bias_ih_attr, bias_hh_attr=bias_hh_attr)

        self.rnns = LayerList()
        for layer_i in range(num_layers):
            in_size = input_size if layer_i == 0 else hidden_size * bidirect
            if bidirect == 2:
                self.rnns.append(BiRNN(cell_cls(in_size, hidden_size, **kwargs),
                                       cell_cls(in_size, hidden_size, **kwargs),
                                       time_major=time_major))
            else:
                self.rnns.append(RNN(cell_cls(in_size, hidden_size, **kwargs),
                                     time_major=time_major))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        out = inputs
        final_h, final_c = [], []
        for i, rnn in enumerate(self.rnns):
            out, st = rnn(out, None)
            if i < self.num_layers - 1 and self.dropout > 0:
                out = F.dropout(out, self.dropout, training=self.training)
            if self.mode == "LSTM":
                if self.num_directions == 2:
                    (h_fw, c_fw), (h_bw, c_bw) = st
                    final_h += [h_fw, h_bw]
                    final_c += [c_fw, c_bw]
                else:
                    final_h.append(st[0])
                    final_c.append(st[1])
            else:
                if self.num_directions == 2:
                    final_h += [st[0], st[1]]
                else:
                    final_h.append(st)
        from ...tensor.manipulation import stack
        if self.mode == "LSTM":
            return out, (stack(final_h, 0), stack(final_c, 0))
        return out, stack(final_h, 0)


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        super().__init__("RNN_TANH", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


# public alias (reference: nn/layer/rnn.py RNNBase)
RNNBase = _RNNBase
