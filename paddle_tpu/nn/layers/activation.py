"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""

from __future__ import annotations

from .. import functional as F
from ..initializer import Constant
from ..layer import Layer

__all__ = [
    "ReLU", "ReLU6", "LeakyReLU", "PReLU", "ELU", "SELU", "CELU", "GELU",
    "Sigmoid", "Hardsigmoid", "Hardswish", "Hardtanh", "Hardshrink",
    "Softshrink", "Tanhshrink", "Swish", "Silu", "Mish", "Softplus",
    "Softsign", "Tanh", "LogSigmoid", "Softmax", "LogSoftmax", "Maxout",
    "ThresholdedReLU", "RReLU", "GLU",
]


def _simple(fn_name, **defaults):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            kw = dict(defaults)
            names = list(defaults.keys())
            for i, a in enumerate(args):
                kw[names[i]] = a
            kw.update({k: v for k, v in kwargs.items() if k in kw})
            self._kw = kw

        def forward(self, x):
            return getattr(F, fn_name)(x, **self._kw)

    _Act.__name__ = fn_name
    return _Act


ReLU = _simple("relu")
ReLU6 = _simple("relu6")
Sigmoid = _simple("sigmoid")
Tanh = _simple("tanh")
Softsign = _simple("softsign")
Silu = _simple("silu")
Swish = _simple("swish")
Mish = _simple("mish")
LogSigmoid = _simple("log_sigmoid")
Tanhshrink = _simple("tanhshrink")
Hardswish = _simple("hardswish")
LeakyReLU = _simple("leaky_relu", negative_slope=0.01)
ELU = _simple("elu", alpha=1.0)
CELU = _simple("celu", alpha=1.0)
SELU = _simple("selu")
GELU = _simple("gelu", approximate=False)
Hardsigmoid = _simple("hardsigmoid")
Hardtanh = _simple("hardtanh", min=-1.0, max=1.0)
Hardshrink = _simple("hardshrink", threshold=0.5)
Softshrink = _simple("softshrink", threshold=0.5)
Softplus = _simple("softplus", beta=1.0, threshold=20.0)
Softmax = _simple("softmax", axis=-1)
LogSoftmax = _simple("log_softmax", axis=-1)
Maxout = _simple("maxout", groups=2, axis=1)
GLU = _simple("glu", axis=-1)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr,
            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self.data_format)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        from ...core.tensor import apply
        import jax.numpy as jnp
        return apply(lambda a: jnp.where(a > self.threshold, a, 0.0), x,
                     name="thresholded_relu")


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower = lower
        self.upper = upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)
