"""Transformer layers.

Reference surface: python/paddle/nn/layer/transformer.py
(MultiHeadAttention:109, TransformerEncoderLayer:437, Transformer:1112).
TPU-native: attention routes through ops.attention (Pallas flash kernel when
eligible), QKV projections are single fused matmuls for MXU efficiency.
"""

from __future__ import annotations

import collections
from typing import Optional

import jax.numpy as jnp

from ...core.tensor import Tensor, apply
from .. import functional as F
from ..layer import Layer, LayerList
from .common import Dropout, Linear
from .norm import LayerNorm

__all__ = [
    "MultiHeadAttention", "TransformerEncoderLayer", "TransformerEncoder",
    "TransformerDecoderLayer", "TransformerDecoder", "Transformer",
]


def _convert_attention_mask(attn_mask, dtype):
    """bool mask (True=keep) -> additive float mask, paddle convention."""
    if attn_mask is None:
        return None
    if attn_mask.dtype == jnp.bool_:
        def _cv(m):
            return jnp.where(m, 0.0, -1e30).astype(jnp.float32)
        return apply(_cv, attn_mask, name="convert_mask")
    return attn_mask


class MultiHeadAttention(Layer):
    """reference: nn/layer/transformer.py:109.

    Inputs [B, S, E]; mask broadcastable to [B, H, Sq, Sk]."""

    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim

        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _shape(self, x):
        # [B, S, E] -> [B, S, H, D]
        from ...tensor.manipulation import reshape
        B, S = x.shape[0], x.shape[1]
        return reshape(x, (B, S, self.num_heads, self.head_dim))

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = query if value is None else value

        q = self._shape(self.q_proj(query))
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._shape(self.k_proj(key))
            v = self._shape(self.v_proj(value))
        if isinstance(cache, self.Cache):
            from ...tensor.manipulation import concat
            k = concat([cache.k, k], axis=1)
            v = concat([cache.v, v], axis=1)
            cache = self.Cache(k, v)

        mask = _convert_attention_mask(attn_mask, None)
        if mask is not None and mask.ndim == 3:
            from ...tensor.manipulation import unsqueeze
            mask = unsqueeze(mask, 1)

        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=mask, dropout_p=self.dropout,
            training=self.training)
        from ...tensor.manipulation import reshape
        B, S = out.shape[0], out.shape[1]
        out = reshape(out, (B, S, self.embed_dim))
        out = self.out_proj(out)
        if cache is not None:
            return out, cache
        return out

    def gen_cache(self, key, value=None, type=None):
        if type == MultiHeadAttention.StaticCache:
            k = self._shape(self.k_proj(key))
            v = self._shape(self.v_proj(value if value is not None else key))
            return self.StaticCache(k, v)
        from ...tensor.creation import zeros
        B = key.shape[0]
        k = zeros((B, 0, self.num_heads, self.head_dim), dtype="float32")
        v = zeros((B, 0, self.num_heads, self.head_dim), dtype="float32")
        return self.Cache(k, v)


class TransformerEncoderLayer(Layer):
    """reference: nn/layer/transformer.py:437."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)

        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    """Encoder stack. ``enable_scan`` (opt-in, set by the model configs —
    BERT/ERNIE default it on) runs the homogeneous stack as ONE
    jax.lax.scan over layer-stacked params (nn.scan): O(1) trace/compile in
    num_layers, per-layer state_dict names unchanged. ``use_recompute`` +
    ``recompute_policy`` select (selective) activation remat for the stack
    (fleet.utils.recompute semantics, composed inside the scanned body)."""

    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList([encoder_layer] +
                                [_clone_layer(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm
        self.enable_scan = False
        self.use_recompute = False
        self.recompute_policy = None

    def forward(self, src, src_mask=None, cache=None):
        from ..scan import can_scan_layers, scan_layers
        if cache is None and self.enable_scan \
                and can_scan_layers(self.layers):
            extra = (src_mask,) if src_mask is not None else ()
            output = scan_layers(
                self.layers, src, *extra,
                use_recompute=self.use_recompute and self.training,
                policy=self.recompute_policy,
                name="encoder_scan_layers")
            if self.norm is not None:
                output = self.norm(output)
            return output
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                if self.use_recompute and self.training:
                    from ...distributed.fleet.utils.recompute import recompute
                    output = recompute(mod, output, src_mask,
                                       policy=self.recompute_policy) \
                        if src_mask is not None else \
                        recompute(mod, output, policy=self.recompute_policy)
                else:
                    output = mod(output, src_mask)
            else:
                output, new_cache = mod(output, src_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                            weight_attr=weight_attr, bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                             weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
            incremental_cache = None
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask, cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
            static_cache = None
        else:
            tgt, static_cache = self.cross_attn(tgt, memory, memory, memory_mask, cache[1])
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (incremental_cache, static_cache))

    def gen_cache(self, memory):
        incremental = self.self_attn.gen_cache(memory)
        static = self.cross_attn.gen_cache(memory, memory,
                                           type=MultiHeadAttention.StaticCache)
        return incremental, static


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList([decoder_layer] +
                                [_clone_layer(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask, memory_mask)
            else:
                output, new_cache = mod(output, memory, tgt_mask, memory_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        cache = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            cache = list(zip(*cache))
        return cache


class Transformer(Layer):
    """reference: nn/layer/transformer.py:1112."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            encoder_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr)
            encoder_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(encoder_layer, num_encoder_layers,
                                              encoder_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            decoder_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr)
            decoder_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(decoder_layer, num_decoder_layers,
                                              decoder_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    def generate_square_subsequent_mask(self, length):
        from ...tensor.creation import tril, ones
        import numpy as np
        m = np.triu(np.full((length, length), -np.inf, np.float32), 1)
        return Tensor(m)


def _clone_layer(layer):
    """Fresh layer with same config but independent, re-drawn parameters
    (the reference constructs each stacked layer separately)."""
    import copy

    from ..initializer import Constant, XavierUniform

    import jax.numpy as jnp

    new = copy.deepcopy(layer)
    xavier = XavierUniform()
    for name, p in new.named_parameters():
        if p.ndim >= 2:
            p._data = xavier(tuple(p.shape), p.dtype)
        else:
            # deepcopy of an (immutable) jax.Array keeps the SAME buffer;
            # re-materialise so clones never alias (buffer donation in
            # TrainStep forbids the same buffer appearing twice)
            p._data = jnp.array(p._data, copy=True)
    for name, b in new.named_buffers():
        b._data = jnp.array(b._data, copy=True)
    return new
