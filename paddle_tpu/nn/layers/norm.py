"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from .. import functional as F
from ..initializer import Constant
from ..layer import Layer

__all__ = [
    "BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D", "SyncBatchNorm",
    "LayerNorm", "GroupNorm", "InstanceNorm1D", "InstanceNorm2D",
    "InstanceNorm3D", "LocalResponseNorm", "SpectralNorm", "RMSNorm",
]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = self.create_parameter(
            (num_features,), attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,), jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,), jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL", name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCW" if data_format == "NCL" else "NLC")


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW", name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batchnorm.

    Reference: nn/layer/norm.py SyncBatchNorm (sync_batch_norm CUDA op).
    TPU-native: inside a pjit/shard_map step the mean/var reduction rides a
    psum over the data axis; eagerly on one host it degrades to BatchNorm.
    """

    def forward(self, x):
        from ...distributed import env as dist_env
        axis = dist_env.current_data_axis()
        if axis is None:
            return super().forward(x)
        from ...core.tensor import apply
        import jax

        mom = self._momentum

        def _sync_bn(a, rm, rv, w, b):
            red = tuple(i for i in range(a.ndim) if i != 1)
            local_mean = jnp.mean(a.astype(jnp.float32), axis=red)
            local_sq = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=red)
            mean = jax.lax.pmean(local_mean, axis)
            sq = jax.lax.pmean(local_sq, axis)
            var = sq - jnp.square(mean)
            shape = [1] * a.ndim
            shape[1] = a.shape[1]
            out = (a - mean.reshape(shape).astype(a.dtype)) * \
                jax.lax.rsqrt(var.reshape(shape) + self._epsilon).astype(a.dtype)
            new_rm = mom * rm + (1 - mom) * jax.lax.stop_gradient(mean)
            new_rv = mom * rv + (1 - mom) * jax.lax.stop_gradient(var)
            return out * w.reshape(shape) + b.reshape(shape), new_rm, new_rv

        if not self.training:
            return super().forward(x)
        eps = self._epsilon

        def _sync_bn_eval(a, rm, rv, w, b):
            shape = [1] * a.ndim
            shape[1] = a.shape[1]
            out = (a - rm.reshape(shape).astype(a.dtype)) * \
                jax.lax.rsqrt(rv.reshape(shape) + eps).astype(a.dtype)
            return out * w.reshape(shape) + b.reshape(shape)

        out, new_rm, new_rv = apply(
            _sync_bn, x, self._mean, self._variance, self.weight,
            self.bias, name="sync_batch_norm")
        from ...core.tensor import annotate_test_variant, record_mutation
        annotate_test_variant(_sync_bn_eval)
        record_mutation(self._mean, new_rm)
        record_mutation(self._variance, new_rv)
        return out

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        """Recursively convert BatchNorm layers to SyncBatchNorm."""
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon,
                                data_format=layer._data_format)
            out.weight = layer.weight
            out.bias = layer.bias
            out._mean = layer._mean
            out._variance = layer._variance
        for name, sub in list(layer._sub_layers.items()):
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter(
            self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """Root-mean-square norm — beyond the reference's surface; standard for
    modern LLM blocks and cheap on the VPU."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=Constant(1.0))

    def forward(self, x):
        from ...core.tensor import apply
        import jax
        eps = self._epsilon
        n = len(self._normalized_shape)

        def _rms(a, w):
            axes = tuple(range(a.ndim - n, a.ndim))
            ms = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=axes, keepdims=True)
            return (a * jax.lax.rsqrt(ms + eps).astype(a.dtype)) * w

        return apply(_rms, x, self.weight, name="rms_norm")


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            (num_channels,), attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = self.create_parameter(
            (num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False or bias_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr, default_initializer=Constant(1.0))
            self.bias = self.create_parameter(
                (num_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Spectral normalization via power iteration
    (reference: nn/layer/norm.py SpectralNorm / spectral_norm op)."""

    def __init__(self, weight_shape, axis=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        self._axis = axis
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[axis]
        w = int(np.prod(weight_shape)) // h
        from ..initializer import Normal
        self.weight_u = self.create_parameter(
            (h,), default_initializer=Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            (w,), default_initializer=Normal(0.0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...core.tensor import apply
        axis, iters, eps = self._axis, self._power_iters, self._epsilon

        def _sn(w, u, v):
            wm = jnp.moveaxis(w, axis, 0)
            mat = wm.reshape(wm.shape[0], -1)
            for _ in range(iters):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ mat @ v
            return w / sigma

        return apply(_sn, weight, self.weight_u, self.weight_v, name="spectral_norm")
