"""Layer: the module base class.

Redesign of the reference's ``fluid.dygraph.Layer``
(reference: python/paddle/fluid/dygraph/layers.py — parameters/sublayers
registries, hooks, state_dict, train/eval). Parameters are eager
:class:`Parameter` tensors; the jit path extracts them as a flat dict pytree
(see paddle_tpu/jit) so the same Layer drives both eager and compiled modes.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax
import numpy as np

from ..core import dtypes
from ..core.tensor import Parameter, Tensor, no_grad
from .initializer import Initializer, ParamAttr, XavierNormal, _resolve_attr

__all__ = ["Layer", "Sequential", "LayerList", "ParameterList", "LayerDict"]


class HookRemoveHelper:
    def __init__(self, hooks: dict, hook_id: int):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self._parameters: Dict[str, Optional[Parameter]] = collections.OrderedDict()
        self._buffers: Dict[str, Optional[Tensor]] = collections.OrderedDict()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._non_persistable_buffer_names: set = set()
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._hook_id = 0
        self.training = True
        self._dtype = dtypes.convert_dtype(dtype)
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- attribute plumbing -------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    params[name] = None
                    return
                del params[name]
            if layers is not None and name in layers:
                del layers[name]
            if buffers is not None and name in buffers:
                if isinstance(value, Tensor) or value is None:
                    buffers[name] = value
                    return
                del buffers[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._buffers) + list(self._sub_layers)

    # -- parameter creation -------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Optional[Parameter]:
        """Create a Parameter (reference: layers.py create_parameter)."""
        if attr is False:
            return None
        dtype = dtypes.convert_dtype(dtype) or self._dtype
        if default_initializer is None:
            from .initializer import Constant, XavierUniform
            default_initializer = Constant(0.0) if is_bias else XavierUniform()
        resolved = _resolve_attr(attr, default_initializer)
        if resolved is None:
            return None
        init, trainable, name = resolved
        data = init(shape, dtype)
        p = Parameter(data, name=name, trainable=trainable)
        if isinstance(attr, ParamAttr):
            p.optimize_attr["learning_rate"] = attr.learning_rate
            p.regularizer = attr.regularizer
        return p

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- traversal ----------------------------------------------------------
    def named_sublayers(self, prefix="", include_self=False, layers_set=None
                        ) -> Iterator[Tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            p = prefix + ("." if prefix else "") + name
            yield from layer.named_sublayers(prefix=p, include_self=True,
                                             layers_set=layers_set)

    def sublayers(self, include_self=False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def named_parameters(self, prefix="", include_sublayers=True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for layer_name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (layer_name + ("." if layer_name else "") + pname, p)

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters()]

    def named_buffers(self, prefix="", include_sublayers=True,
                      include_non_persistable=True
                      ) -> Iterator[Tuple[str, Tensor]]:
        seen = set()
        for layer_name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                if (not include_non_persistable
                        and bname in layer._non_persistable_buffer_names):
                    continue
                yield (layer_name + ("." if layer_name else "") + bname, b)

    def buffers(self, include_sublayers=True) -> List[Tensor]:
        return [b for _, b in self.named_buffers()]

    # -- mode & application -------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn: Callable[["Layer"], None]):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dtype = dtypes.convert_dtype(dtype)
        with no_grad():
            for _, p in self.named_parameters():
                d = dtype if (dtype is not None and dtypes.is_floating_point(p.dtype)) else None
                new = p.to(device=device, dtype=d)
                p._data = new._data
            for _, b in self.named_buffers():
                d = dtype if (dtype is not None and dtypes.is_floating_point(b.dtype)) else None
                new = b.to(device=device, dtype=d)
                b._data = new._data
        if dtype is not None:
            self._dtype = dtype
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call ---------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True) -> Dict[str, Tensor]:
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix):
            dest[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix,
                                          include_non_persistable=False):
            dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Load values into existing parameters/buffers (shape-checked)."""
        own = self.state_dict()
        missing, unexpected = [], []
        for name, value in state_dict.items():
            if name not in own:
                unexpected.append(name)
                continue
            target = own[name]
            arr = value.data if isinstance(value, Tensor) else np.asarray(value)
            if tuple(arr.shape) != tuple(target.shape):
                raise ValueError(
                    f"shape mismatch for {name}: got {tuple(arr.shape)}, "
                    f"expected {tuple(target.shape)}")
            import jax.numpy as jnp
            target._data = jnp.asarray(arr, target.dtype)
        for name in own:
            if name not in state_dict:
                missing.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # -- misc ---------------------------------------------------------------
    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            rep = repr(layer).split("\n")
            rep = [rep[0]] + ["  " + r for r in rep[1:]]
            lines.append(f"  ({name}): " + "\n".join(rep))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"


class Sequential(Layer):
    """reference: python/paddle/fluid/dygraph/container.py Sequential"""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], collections.OrderedDict):
            for name, layer in layers[0].items():
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                if isinstance(layer, tuple):
                    self.add_sublayer(layer[0], layer[1])
                else:
                    self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers.keys())
        return self._sub_layers[keys[idx]]

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return self._sub_layers[str(idx if idx >= 0 else len(self) + idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self.add_sublayer(str(i), l)

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self)), parameter)
        return self


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()

    def update(self, sublayers):
        items = sublayers.items() if isinstance(sublayers, dict) else sublayers
        for key, layer in items:
            self.add_sublayer(key, layer)
        return self

    def pop(self, key):
        layer = self._sub_layers[key]
        del self._sub_layers[key]
        return layer

    def clear(self):
        self._sub_layers.clear()
