"""Internal-layout planner: channels-last persistence for vision stacks.

TPU MXU convolutions are NHWC-native; the public API contract of this
framework (like the reference's) is NCHW. Before this module, every
conv/pool/BN in an NCHW model carried NCHW dimension numbers into XLA and
paid per-op layout churn. The planner instead runs whole
conv/BN/activation/pool chains channels-last END TO END:

- a thread-local :func:`channels_last_scope` marks a region (a vision
  model's feature extractor, or a whole jitted train step — see
  ``TrainStep`` and ``FLAGS_jit_channels_last``);
- the FIRST conv2d inside the scope transposes its NCHW input to NHWC
  once (``layout_entry``) and tags the output tensor (``Tensor._layout ==
  "NHWC"``);
- layout-AWARE ops (conv2d, batch_norm, the 2-D pools, fused_conv_bn)
  consume the tag natively — they run with channels-last dimension
  numbers / channel axis and re-tag their outputs;
- layout-TRANSPARENT ops (elementwise activations, add/mul, dropout, ...)
  propagate the tag through ``apply`` without touching data;
- the first layout-UNAWARE op (flatten, reshape, matmul, ...) gets a
  single ``layout_exit`` transpose back to NCHW inserted in front of it.

Net effect: one transpose at model entry, one at exit, NHWC convs in
between — while the user-facing NCHW API contract is unchanged (see
docs/PARITY.md, internal-layout contract).

The hooks are installed into ``core.tensor.apply`` at import and are
no-ops (one thread-local read) unless a scope is active on the calling
thread.
"""

from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from ..core.tensor import Tensor, apply, set_layout_hooks

__all__ = ["channels_last_scope", "check_data_format", "is_active",
           "layout_of", "to_channels_last", "to_channels_first"]


def check_data_format(data_format: str) -> str:
    """Validate a vision model's 2-D data_format flag (shared by the whole
    model zoo)."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(
            f"data_format must be 'NCHW' or 'NHWC', got {data_format!r}")
    return data_format

_tls = threading.local()


def is_active() -> bool:
    return getattr(_tls, "active", 0) > 0


@contextlib.contextmanager
def channels_last_scope(enable: bool = True):
    """Activate the channels-last planner for ops issued inside the block.

    Reentrant; ``enable=False`` is a no-op block so call sites can make
    the fast path conditional without branching.
    """
    if not enable:
        yield
        return
    _tls.active = getattr(_tls, "active", 0) + 1
    try:
        yield
    finally:
        _tls.active -= 1


def layout_of(t) -> str:
    return getattr(t, "_layout", None) or "NCHW"


def tag(t: Tensor) -> Tensor:
    t._layout = "NHWC"
    return t


# closure-free module-level transposes: eligible for the eager op cache
def _nchw_to_nhwc(a):
    return jnp.transpose(a, (0, 2, 3, 1))


def _nhwc_to_nchw(a):
    return jnp.transpose(a, (0, 3, 1, 2))


def to_channels_last(t: Tensor) -> Tensor:
    """The single entry transpose: NCHW tensor -> tagged NHWC tensor."""
    return tag(apply(_nchw_to_nhwc, t, name="layout_entry"))


def to_channels_first(t: Tensor) -> Tensor:
    """The single exit transpose: tagged NHWC tensor -> NCHW tensor."""
    out = apply(_nhwc_to_nchw, t, name="layout_exit")
    out._layout = None
    return out


def ensure_channels_first(t):
    """Model-boundary guard: restore NCHW if ``t`` is still tagged. Vision
    model forwards call this on their return value so a headless/unpooled
    configuration never leaks the internal NHWC layout to the caller."""
    if isinstance(t, Tensor) and getattr(t, "_layout", None) == "NHWC":
        return to_channels_first(t)
    return t


# Ops that handle the NHWC tag themselves (consume + re-tag); the pre-hook
# must not rewrite their inputs. layout_entry/exit are here so the hook
# never recurses into its own transposes.
_AWARE = frozenset({
    "conv2d", "fused_conv_bn", "batch_norm",
    "max_pool2d", "avg_pool2d", "adaptive_avg_pool2d", "adaptive_max_pool2d",
    "layout_entry", "layout_exit",
})

# Elementwise ops that preserve shape and therefore layout: the tag rides
# through them untouched (post-hook re-tags the output when its shape
# matches the tagged input's). Anything NOT listed here or in _AWARE gets
# the exit transpose — correctness never depends on this list being
# complete, only the persistence distance does.
_TRANSPARENT = frozenset({
    "relu", "relu6", "leaky_relu", "elu", "selu", "celu", "gelu",
    "sigmoid", "hardsigmoid", "hardswish", "hardtanh", "hardshrink",
    "softshrink", "tanhshrink", "silu", "swish", "mish", "softplus",
    "softsign", "tanh", "log_sigmoid",
    "add", "subtract", "multiply", "divide", "scale", "clip",
    "maximum", "minimum", "pow", "abs", "neg", "sqrt", "square", "exp",
    "dropout", "alpha_dropout",
})


def _exit_tagged(args):
    return tuple(
        to_channels_first(a)
        if isinstance(a, Tensor) and getattr(a, "_layout", None) == "NHWC"
        else a
        for a in args)


def _pre(name: str, args):
    """apply() pre-hook: insert the exit transpose in front of a
    layout-unaware op consuming a tagged tensor, and in front of a
    transparent op whose operands MIX layouts."""
    if not is_active() or name in _AWARE:
        return args
    if not any(isinstance(a, Tensor)
               and getattr(a, "_layout", None) == "NHWC" for a in args):
        return args
    if name in _TRANSPARENT:
        # Mixed-layout guard: a transparent elementwise op may combine a
        # tagged (physically NHWC) tensor only with python scalars, 0-d
        # tensors, or other tagged tensors — an untagged tensor operand
        # with axes is NCHW-world data whose broadcast would silently bind
        # to permuted axes (even 1-D: trailing-axis broadcast means W in
        # NCHW but C in NHWC). Fall back to NCHW for this op instead.
        mixed = any(
            isinstance(a, Tensor)
            and getattr(a, "_layout", None) != "NHWC"
            and a._data.ndim >= 1
            for a in args)
        if not mixed:
            return args
    return _exit_tagged(args)


def _post(name: str, args, result):
    """apply() post-hook: propagate the tag through transparent ops."""
    if not is_active() or name not in _TRANSPARENT:
        return
    if not isinstance(result, Tensor) or result._data.ndim != 4:
        return
    for a in args:
        if isinstance(a, Tensor) \
                and getattr(a, "_layout", None) == "NHWC" \
                and a._data.shape == result._data.shape:
            result._layout = "NHWC"
            return


set_layout_hooks(_pre, _post)
