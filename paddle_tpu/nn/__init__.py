"""paddle_tpu.nn — layer library (reference surface: python/paddle/nn/)."""

from . import functional  # noqa: F401
from . import chunked_ce  # noqa: F401  (streamed-vocab cross entropy)
from . import layout  # noqa: F401  (installs the channels-last planner hooks)
from . import scan  # noqa: F401  (scan-over-layers for homogeneous stacks)
from . import initializer  # noqa: F401
from .initializer import ParamAttr  # noqa: F401
from .layer import Layer, LayerDict, LayerList, ParameterList, Sequential  # noqa: F401

from .layers.activation import *  # noqa: F401,F403
from .layers.common import *  # noqa: F401,F403
from .layers.conv import *  # noqa: F401,F403
from .layers.loss import *  # noqa: F401,F403
from .layers.norm import *  # noqa: F401,F403
from .layers.pooling import *  # noqa: F401,F403
from .layers.rnn import *  # noqa: F401,F403
from .layers.transformer import *  # noqa: F401,F403
from .decode import (BeamSearchDecoder, Decoder,  # noqa: F401
                     dynamic_decode, gather_tree)
from . import quant  # noqa: F401
from . import utils  # noqa: F401

from ..core.tensor import Parameter  # noqa: F401


def __getattr__(name):
    if name in ("ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue"):
        from ..optimizer import clip
        return getattr(clip, name)
    raise AttributeError(f"module 'paddle_tpu.nn' has no attribute {name!r}")
