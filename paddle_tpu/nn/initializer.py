"""Parameter initializers (reference: python/paddle/fluid/initializer.py,
python/paddle/nn/initializer/).

Each initializer is a callable ``(shape, dtype) -> jax array`` drawing from
the global RNG (core/random.py)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtypes
from ..core.random import make_rng

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain",
]


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    return gains[nonlinearity]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: paddle layout [out_c, in_c, *spatial]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype=None):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=None):
        return jnp.full(tuple(shape), self.value,
                        dtypes.convert_dtype(dtype) or dtypes.get_default_dtype())


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        return jax.random.normal(make_rng(), tuple(shape), d) * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None):
        d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        return (jax.random.truncated_normal(make_rng(), -2.0, 2.0, tuple(shape), d)
                * self.std + self.mean)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=None):
        d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        return jax.random.uniform(make_rng(), tuple(shape), d, self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, gain=1.0, fan_in=None, fan_out=None):
        self.gain, self.fan_in, self.fan_out = gain, fan_in, fan_out

    def __call__(self, shape, dtype=None):
        d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return jax.random.normal(make_rng(), tuple(shape), d) * std


class XavierUniform(Initializer):
    def __init__(self, gain=1.0, fan_in=None, fan_out=None):
        self.gain, self.fan_in, self.fan_out = gain, fan_in, fan_out

    def __call__(self, shape, dtype=None):
        d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(make_rng(), tuple(shape), d, -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype=None):
        d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.slope)
        std = gain / math.sqrt(fi)
        return jax.random.normal(make_rng(), tuple(shape), d) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype=None):
        d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(make_rng(), tuple(shape), d, -limit, limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=None):
        d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        arr = jnp.asarray(np.asarray(self.value), d)
        if tuple(arr.shape) != tuple(shape):
            raise ValueError(f"Assign shape {arr.shape} != param shape {tuple(shape)}")
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=None):
        d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        return jax.nn.initializers.orthogonal(self.gain)(make_rng(), tuple(shape), d)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=None):
        d = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        out = np.zeros(tuple(shape), np.float32)
        oc, ic = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(min(oc // self.groups, ic)):
                idx = (g * (oc // self.groups) + i, i, *centers)
                out[idx] = 1.0
        return jnp.asarray(out, d)


# paddle.ParamAttr analogue ---------------------------------------------------
class ParamAttr:
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


def _resolve_attr(attr, default_init):
    """Normalise a param_attr/bias_attr argument to (initializer, trainable, name)."""
    if attr is False:
        return None
    if attr is None:
        return (default_init, True, None)
    if isinstance(attr, ParamAttr):
        return (attr.initializer or default_init, attr.trainable, attr.name)
    if isinstance(attr, Initializer):
        return (attr, True, None)
    if isinstance(attr, str):
        return (default_init, True, attr)
    raise TypeError(f"Unsupported param attr: {attr!r}")
