"""Chunked (streamed-vocab) softmax cross-entropy.

The dense composition (``log_softmax(logits.astype(f32))`` + gather)
materializes TWO full-vocab f32 tensors per loss — for GPT-2 345M at
B=8/S=1024 that is 2 x 1.65 GB of HBM traffic on top of the bf16 logits,
and the backward touches them again. This module is the Rabe & Staats-style
online-softmax formulation of the same loss: a ``custom_vjp`` op that
streams over vocab chunks with an online (max, sum) logsumexp recurrence,
accumulating in f32 while only ever holding ONE ``[N, chunk]`` f32 tile —
the full-vocab f32 logits/log-probs are never built, forward or backward.

Numerics: the online logsumexp is exact up to f32 rounding (same
accumulation dtype as the dense path), the backward is the closed form
``softmax - onehot`` (hard) / ``sum(t)*softmax - t`` (soft) written
chunk-by-chunk in the logits dtype. ``ignore_index`` / class weights /
reduction stay OUTSIDE the kernel (plain differentiable epilogue), so the
public ``cross_entropy`` semantics are preserved bit-for-bit in structure.

Vocab sizes that are not a multiple of the chunk are handled by clamping
the last chunk's start and masking the overlap columns — no padding copy
of the logits is made.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..core.flags import get_flag

__all__ = ["enabled_for", "chunk_size_for", "hard_nll", "soft_nll",
           "masked_lm_loss"]


def enabled_for(vocab_size: int) -> bool:
    """True when the streamed path should serve this vocab size."""
    thr = int(get_flag("chunked_ce_threshold"))
    return thr > 0 and int(vocab_size) >= thr


def chunk_size_for(vocab_size: int) -> int:
    return max(1, min(int(get_flag("chunked_ce_chunk")), int(vocab_size)))


def _chunk_bounds(i, chunk: int, V: int):
    """Clamped slice start + validity mask for chunk ``i``.

    The last chunk of a non-multiple vocab starts at ``V - chunk`` (so the
    slice stays in bounds) and masks the columns that belong to the
    previous chunk; full chunks are fully valid."""
    start = i * chunk
    astart = jnp.minimum(start, V - chunk)
    cols = astart + jnp.arange(chunk, dtype=jnp.int32)
    valid = (cols >= start) & (cols < V)
    return astart, cols, valid


def _online_lse(logits, chunk: int):
    """Row logsumexp of ``[N, V]`` logits via the online (m, s) recurrence,
    f32 accumulators, one [N, chunk] f32 tile live at a time."""
    N, V = logits.shape
    num_chunks = -(-V // chunk)

    def body(i, carry):
        m, s = carry
        astart, _, valid = _chunk_bounds(i, chunk, V)
        sl = jax.lax.dynamic_slice_in_dim(logits, astart, chunk, axis=1)
        sl = jnp.where(valid[None, :], sl.astype(jnp.float32), -jnp.inf)
        nm = jnp.maximum(m, jnp.max(sl, axis=1))
        s = s * jnp.exp(m - nm) + jnp.sum(
            jnp.where(valid[None, :], jnp.exp(sl - nm[:, None]), 0.0),
            axis=1)
        return nm, s

    m0 = jnp.full((N,), -jnp.inf, jnp.float32)
    s0 = jnp.zeros((N,), jnp.float32)
    m, s = jax.lax.fori_loop(0, num_chunks, body, (m0, s0))
    return m + jnp.log(s)


def _int_zero_cotangent(x):
    """float0 cotangent for an integer primal (labels)."""
    return np.zeros(x.shape, dtype=jax.dtypes.float0)


# ---------------------------------------------------------------------------
# hard labels: loss[n] = lse(logits[n]) - logits[n, labels[n]]
# ---------------------------------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(0,))
def _ce_hard(chunk: int, logits, labels):
    loss, _ = _ce_hard_fwd(chunk, logits, labels)
    return loss


def _ce_hard_fwd(chunk: int, logits, labels):
    lse = _online_lse(logits, chunk)
    tgt = jnp.take_along_axis(logits, labels[:, None], axis=1)[:, 0]
    loss = lse - tgt.astype(jnp.float32)
    return loss, (logits, labels, lse)


def _ce_hard_bwd(chunk: int, res, g):
    logits, labels, lse = res
    N, V = logits.shape
    num_chunks = -(-V // chunk)
    g32 = g.astype(jnp.float32)

    def body(i, grad):
        astart, cols, valid = _chunk_bounds(i, chunk, V)
        sl = jax.lax.dynamic_slice_in_dim(logits, astart, chunk, axis=1)
        p = jnp.exp(sl.astype(jnp.float32) - lse[:, None])
        onehot = (cols[None, :] == labels[:, None]).astype(jnp.float32)
        d = ((p - onehot) * g32[:, None]).astype(grad.dtype)
        # read-modify-write: the clamped last chunk overlaps the previous
        # one; overlap columns keep their already-written values
        cur = jax.lax.dynamic_slice_in_dim(grad, astart, chunk, axis=1)
        return jax.lax.dynamic_update_slice_in_dim(
            grad, jnp.where(valid[None, :], d, cur), astart, axis=1)

    grad = jax.lax.fori_loop(0, num_chunks, body, jnp.zeros_like(logits))
    return grad, _int_zero_cotangent(labels)


_ce_hard.defvjp(_ce_hard_fwd, _ce_hard_bwd)


# ---------------------------------------------------------------------------
# soft labels: loss[n] = sum_v t[n,v] * (lse[n] - logits[n,v])
# ---------------------------------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(0,))
def _ce_soft(chunk: int, logits, target):
    loss, _ = _ce_soft_fwd(chunk, logits, target)
    return loss


def _ce_soft_fwd(chunk: int, logits, target):
    N, V = logits.shape
    num_chunks = -(-V // chunk)

    def body(i, carry):
        tl, tsum = carry
        astart, _, valid = _chunk_bounds(i, chunk, V)
        sl = jax.lax.dynamic_slice_in_dim(logits, astart, chunk, axis=1)
        tc = jax.lax.dynamic_slice_in_dim(target, astart, chunk, axis=1)
        sl32 = jnp.where(valid[None, :], sl.astype(jnp.float32), 0.0)
        tc32 = jnp.where(valid[None, :], tc.astype(jnp.float32), 0.0)
        return tl + jnp.sum(tc32 * sl32, axis=1), tsum + jnp.sum(tc32, axis=1)

    lse = _online_lse(logits, chunk)
    z = jnp.zeros((N,), jnp.float32)
    tl, tsum = jax.lax.fori_loop(0, num_chunks, body, (z, z))
    loss = tsum * lse - tl
    return loss, (logits, target, lse, tsum)


def _ce_soft_bwd(chunk: int, res, g):
    logits, target, lse, tsum = res
    N, V = logits.shape
    num_chunks = -(-V // chunk)
    g32 = g.astype(jnp.float32)

    def body(i, carry):
        grad_l, grad_t = carry
        astart, _, valid = _chunk_bounds(i, chunk, V)
        sl = jax.lax.dynamic_slice_in_dim(logits, astart, chunk, axis=1)
        tc = jax.lax.dynamic_slice_in_dim(target, astart, chunk, axis=1)
        sl32 = sl.astype(jnp.float32)
        p = jnp.exp(sl32 - lse[:, None])
        dl = ((tsum[:, None] * p - tc.astype(jnp.float32))
              * g32[:, None]).astype(grad_l.dtype)
        dt = ((lse[:, None] - sl32) * g32[:, None]).astype(grad_t.dtype)
        cur_l = jax.lax.dynamic_slice_in_dim(grad_l, astart, chunk, axis=1)
        cur_t = jax.lax.dynamic_slice_in_dim(grad_t, astart, chunk, axis=1)
        grad_l = jax.lax.dynamic_update_slice_in_dim(
            grad_l, jnp.where(valid[None, :], dl, cur_l), astart, axis=1)
        grad_t = jax.lax.dynamic_update_slice_in_dim(
            grad_t, jnp.where(valid[None, :], dt, cur_t), astart, axis=1)
        return grad_l, grad_t

    grad_l, grad_t = jax.lax.fori_loop(
        0, num_chunks, body,
        (jnp.zeros_like(logits), jnp.zeros_like(target)))
    return grad_l, grad_t


_ce_soft.defvjp(_ce_soft_fwd, _ce_soft_bwd)


# ---------------------------------------------------------------------------
# raw-array helpers (reshape leading dims, pick the chunk width)
# ---------------------------------------------------------------------------


def hard_nll(logits, labels, chunk: int = None):
    """Streamed per-position NLL. ``logits [..., V]``, ``labels [...]``
    integer class ids (caller maps ignore_index to a safe id and masks the
    result). Returns f32 ``[...]`` losses.

    Served by the fused Pallas kernel (ops.pallas.chunked_ce) when
    ``FLAGS_pallas_ce`` is on and the backend can run it; the pure-XLA
    fori_loop streaming op below is the kill-switch fallback (and the
    only soft-label path)."""
    V = logits.shape[-1]
    lead = logits.shape[:-1]
    chunk = min(chunk or chunk_size_for(V), V)
    from ..ops import pallas as pallas_ops
    if pallas_ops.kernel_enabled("chunked_ce"):
        from ..ops.pallas.chunked_ce import chunked_ce_loss
        loss = chunked_ce_loss(logits.reshape((-1, V)),
                               labels.reshape((-1,)).astype(jnp.int32),
                               int(chunk))
    else:
        loss = _ce_hard(int(chunk), logits.reshape((-1, V)),
                        labels.reshape((-1,)).astype(jnp.int32))
    return loss.reshape(lead)


def soft_nll(logits, target, chunk: int = None):
    """Streamed per-position soft-label CE. ``logits/target [..., V]``.
    Returns f32 ``[...]`` losses."""
    V = logits.shape[-1]
    lead = logits.shape[:-1]
    chunk = min(chunk or chunk_size_for(V), V)
    loss = _ce_soft(int(chunk), logits.reshape((-1, V)),
                    target.reshape((-1, V)))
    return loss.reshape(lead)


def masked_lm_loss(logits, labels, *weights, chunked: bool = None):
    """Shared tied-MLM-head loss epilogue (BERT/ERNIE): per-position NLL —
    streamed above the vocab threshold, dense logsumexp+gather below —
    with optional per-position weights and mean reduction. Raw arrays
    (call inside ``apply``); pass ``chunked`` resolved OUTSIDE the closure
    so the path choice is stable for any cached trace.

    (ParallelCrossEntropy keeps its own dense composition: its explicit
    stop-gradient max-shift is what GSPMD partitions across vocab shards
    on the mp path.)"""
    ids = labels.astype(jnp.int32)
    if chunked is None:
        chunked = enabled_for(logits.shape[-1])
    if chunked:
        per = hard_nll(logits, ids)
    else:
        lg32 = logits.astype(jnp.float32)
        lse = jax.nn.logsumexp(lg32, axis=-1)
        tgt = jnp.take_along_axis(lg32, ids[..., None], axis=-1)[..., 0]
        per = lse - tgt
    if weights:
        m = weights[0].astype(jnp.float32)
        return jnp.sum(per * m) / jnp.maximum(jnp.sum(m), 1.0)
    return jnp.mean(per)
