"""Scan-over-layers: homogeneous layer stacks as ONE ``jax.lax.scan``.

The TPU-native answer to the O(num_layers) trace/compile cost of running a
decoder stack as a Python loop over ``LayerList`` (the reference traces one
sub-graph per layer; XLA then compiles L inlined copies of the same block).
Here the per-layer parameters are stacked along a new leading axis and the
block body is traced ONCE as the scan body — the T5X/MaxText
scan-over-stacked-params recipe:

- trace + compile cost: O(1) in the number of layers (the headline win:
  20-30s cold compiles on 24-layer stacks collapse to the single-block
  cost);
- the public surface is unchanged: parameters stay stored per layer on the
  real blocks (``layers.0.attn.qkv_weight`` state_dict names, ``LayerList``
  indexing/iteration, per-layer ``Parameter.spec`` TP shardings) — the
  stack is an internal, trace-time layout (docs/PARITY.md);
- selective remat composes INSIDE the body: ``jax.checkpoint(body,
  policy=...)`` saves MXU outputs and rematerializes the elementwise tail
  (``prevent_cse=False`` per the jax guidance for remat-in-scan);
- RNG: each layer folds its index into the scan's base key, so dropout
  masks stay distinct per layer (the loop path draws per-layer keys from
  the trace counter instead — same distribution, different realization).

Gradient flow in eager mode rides the tape: the per-layer parameter stack
is the taped ``stack`` op (its VJP unstacks cotangents back onto each
block's Parameter) and the scan itself is one taped ``apply`` node.
"""

from __future__ import annotations

import contextlib
import warnings
import weakref

import jax
import jax.numpy as jnp

from ..core.flags import get_flag
from ..core.random import make_rng, trace_rng
from ..core.tensor import Tensor, apply

__all__ = ["can_scan_layers", "scan_layers", "scan_layers_with_cache",
           "invalidate_scan_cache", "note_scan_fallback", "SCAN_STATS"]

#: observability for the trace-count assertion helper
#: (paddle_tpu.utils.compilation): ``body_traces`` counts how many times a
#: scan body was traced at the Python level — pinned by tests to be
#: independent of the number of layers. ``fallbacks`` counts
#: :func:`note_scan_fallback` calls (stacks that were scan-eligible but
#: degraded to the Python loop, e.g. legacy KV-cache decode).
SCAN_STATS = {"body_traces": 0, "scan_calls": 0, "fallbacks": 0}

#: (reason, stack) pairs already warned about — the fallback warning is
#: one-time per cause so a decode loop does not spam stderr per step
_FALLBACK_WARNED: set = set()


def reset_scan_stats():
    SCAN_STATS["body_traces"] = 0
    SCAN_STATS["scan_calls"] = 0
    SCAN_STATS["fallbacks"] = 0
    _FALLBACK_WARNED.clear()


def note_scan_fallback(reason: str, stack: str = "") -> None:
    """Record that an otherwise scan-eligible stack ran as the Python
    loop — the silent-degradation path this exists to make loud.

    Emits a one-time RuntimeWarning per (reason, stack) naming the cause,
    bumps ``SCAN_STATS['fallbacks']`` always, and (monitor mode) a
    ``scan_fallback_total`` registry counter. Known reasons:
    ``legacy_static_cache`` (list-of-StaticCache decode predates the
    paged layout and has per-layer python state the scan cannot carry),
    ``scan_decode_disabled`` (FLAGS_scan_decode kill switch).
    """
    SCAN_STATS["fallbacks"] += 1
    key = (reason, stack)
    if key not in _FALLBACK_WARNED:
        _FALLBACK_WARNED.add(key)
        warnings.warn(
            f"scan-over-layers fell back to the per-layer Python loop for "
            f"{stack or 'a layer stack'} (reason: {reason}); trace/compile "
            "cost is O(num_layers) on this path. Paged-KV decode "
            "(paddle_tpu.serving) runs under scan; FLAGS_scan_decode "
            "controls it.", RuntimeWarning, stacklevel=3)
    from ..monitor import enabled as _mon_enabled
    if _mon_enabled():
        from ..monitor import get_registry
        get_registry().counter(
            "scan_fallback_total",
            "scan-eligible stacks that degraded to the per-layer Python "
            "loop, by cause").inc(reason=reason, stack=stack)


def _config_sig(block):
    """Per-block NON-parameter config fingerprint: simple-typed attributes
    and callables (activation fns) on every sublayer. The scan body runs
    every layer through block[0]'s forward, so per-layer config divergence
    the param signature cannot see (a hand-tuned ``layers[i].dropout.p``,
    a swapped activation on the same class) must veto the scan. Callables
    compare by IDENTITY — distinct lambdas share a ``__qualname__`` but
    are different functions."""
    sig = []
    for path, lyr in block.named_sublayers(include_self=True):
        for k in sorted(vars(lyr)):
            if k.startswith("_") or k == "training":
                continue
            v = vars(lyr)[k]
            if isinstance(v, (int, float, bool, str, type(None))):
                sig.append((path, k, v))
            elif callable(v) and not hasattr(v, "named_parameters"):
                sig.append((path, k, id(v)))
    return tuple(sig)


#: cached per-stack config-homogeneity verdicts, keyed on the LayerList —
#: the vars() walk over every sublayer is the expensive part of the scan
#: eligibility check and cannot change without someone mutating a layer
#: in place (see invalidate_scan_cache). Invalidated automatically when
#: the stack's membership changes (block identity token).
_CFG_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def invalidate_scan_cache(blocks=None):
    """Drop cached scan-eligibility verdicts (all, or one stack's).

    The per-layer CONFIG check runs once per stack membership and is then
    cached; editing a layer's non-parameter attribute in place AFTER the
    stack has been used (e.g. ``layers[i].dropout.p = ...``) needs this
    call (or a ``FLAGS_scan_layers`` toggle) for the next forward to
    re-evaluate. Parameter replacement/reshape is always re-checked."""
    if blocks is None:
        _CFG_CACHE.clear()
    else:
        try:
            _CFG_CACHE.pop(blocks, None)
        except TypeError:
            pass


def _configs_homogeneous(blocks_obj, blocks) -> bool:
    token = tuple(id(b) for b in blocks)
    try:
        ent = _CFG_CACHE.get(blocks_obj)
    except TypeError:
        ent = None
    if ent is not None and ent[0] == token:
        return ent[1]
    ok = len({_config_sig(b) for b in blocks}) == 1
    try:
        _CFG_CACHE[blocks_obj] = (token, ok)
    except TypeError:
        pass                      # plain list / non-weakrefable container
    return ok


def can_scan_layers(blocks) -> bool:
    """True when ``blocks`` is a homogeneous stack the scan path can run:
    >= 2 layers of the same class with identical parameter names/shapes/
    dtypes, identical non-parameter config (:func:`_config_sig`, verdict
    cached per stack — see :func:`invalidate_scan_cache`), and no buffers
    (running-stat layers need per-layer state threading the scan does not
    model)."""
    if not get_flag("scan_layers"):
        return False
    blocks_obj = blocks
    blocks = list(blocks)
    if len(blocks) < 2:
        return False
    cls = type(blocks[0])
    ref = None
    for b in blocks:
        if type(b) is not cls:
            return False
        if any(True for _ in b.named_buffers()):
            return False
        sig = tuple((n, tuple(p.shape), str(p.dtype))
                    for n, p in b.named_parameters())
        if ref is None:
            ref = sig
        elif sig != ref:
            return False
    if not ref:
        return False
    # LIVE check (not cached): per-layer train/eval heterogeneity — the
    # body would apply block[0]'s mode to every layer. model.train()/
    # .eval() set all blocks uniformly; a hand-frozen subset must veto.
    if len({bool(b.training) for b in blocks}) > 1:
        return False
    return _configs_homogeneous(blocks_obj, blocks)


def scan_layers(blocks, x, *extra, policy=None, use_recompute: bool = False,
                num_aux: int = 0, token_extra=None,
                name: str = "scan_layers"):
    """Run ``x`` through ``blocks`` sequentially via one ``jax.lax.scan``.

    ``blocks``: homogeneous Layers (pre-validated with
    :func:`can_scan_layers`). ``extra``: broadcast (non-scanned) Tensor
    arguments passed to every block call, e.g. an attention mask.
    ``policy``: a ``jax.checkpoint_policies`` predicate (or name — see
    ``fleet.utils.recompute.resolve_checkpoint_policy``) for selective
    remat; only applied when ``use_recompute``.

    ``num_aux``: when > 0, each block's forward returns ``(x, aux_1, ...,
    aux_{num_aux})`` and the per-layer aux values leave the scan as
    scanned-over outputs stacked ``[L, ...]`` — the side channel MoE
    stacks use for per-layer router losses/stats (a value produced
    inside the scan body can only escape as a scan output; storing it on
    the layer would leak a body tracer). The call then returns
    ``(y, aux_1_stacked, ..., aux_n_stacked)``.

    Returns the final hidden states Tensor (or the tuple above).
    Equivalent to ``for b in blocks: x = b(x, *extra)`` up to float
    reassociation (and dropout-mask realization when training with
    dropout).
    """
    from ..distributed.fleet.utils.recompute import resolve_checkpoint_policy
    from ..jit.functional import bind

    blocks = list(blocks)
    template = blocks[0]
    num_layers = len(blocks)
    policy = resolve_checkpoint_policy(policy)

    names = [n for n, _ in template.named_parameters()]
    specs = {n: getattr(p, "spec", None)
             for n, p in template.named_parameters()}
    per_block = [dict(b.named_parameters()) for b in blocks]

    # every block's Parameters enter the ONE apply below directly
    # (name-major order); the [L, ...] stacks are built INSIDE the traced
    # fn, so eager backward unstacks cotangents onto each block's own
    # Parameter via this op's VJP — no per-call taped stack ops, and warm
    # eager steps are a single cached-jit dispatch
    flat_params = [pb[n] for n in names for pb in per_block]

    # one base key per scan call; layers fold in their index, so masks are
    # distinct per layer and per step. Eval-mode forwards never consume
    # randomness — skip the key entirely so inference jaxprs (ONNX/export
    # consumers) carry no PRNG constants or dead fold_in ops. The key is
    # an ARGUMENT (not a closure capture): the eager jit-op cache replays
    # a cached trace, and a captured key would freeze the first step's
    # dropout masks forever.
    training = bool(getattr(template, "training", True))
    key_args = ()
    if training:
        k = make_rng(None)
        key_args = (k._data if isinstance(k, Tensor) else k,)

    SCAN_STATS["scan_calls"] += 1

    def _scan_fn(x_arr, *arrs):
        if training:
            key, arrs = arrs[0], arrs[1:]
        else:
            key = None
        n_p = len(names) * num_layers
        p_stacked = {
            n: jnp.stack(arrs[i * num_layers:(i + 1) * num_layers], axis=0)
            for i, n in enumerate(names)}
        extra_raw = arrs[n_p:]
        # pin the stacked layout to the per-layer TP specs (leading layer
        # axis replicated); no-op without an active mesh
        from ..distributed.spmd import constrain
        for n in names:
            sp = specs[n]
            if sp is not None:
                p_stacked[n] = constrain(p_stacked[n], None, *tuple(sp))

        def body(carry, xs):
            SCAN_STATS["body_traces"] += 1
            p_slice, idx = xs
            rng_ctx = (trace_rng(jax.random.fold_in(key, idx))
                       if key is not None else contextlib.nullcontext())
            with rng_ctx, bind(template, p_slice, None):
                out = template(Tensor(carry),
                               *[Tensor(e) if hasattr(e, "dtype") else e
                                 for e in extra_raw])
            aux_raw = None
            if num_aux:
                out, aux = out[0], tuple(out[1:1 + num_aux])
                aux_raw = tuple(a._data if isinstance(a, Tensor) else a
                                for a in aux)
            out = out._data if isinstance(out, Tensor) else out
            return out.astype(carry.dtype), aux_raw

        if use_recompute:
            # prevent_cse=False: inside scan the loop structure already
            # rules out the CSE hazard jax.checkpoint guards against
            body = jax.checkpoint(body, policy=policy, prevent_cse=False)
        y, ys = jax.lax.scan(
            body, x_arr,
            (p_stacked, jnp.arange(num_layers, dtype=jnp.int32)))
        if num_aux:
            return (y,) + tuple(ys)
        return y

    x_t = x if isinstance(x, Tensor) else Tensor(x)
    # token-keyed eager jit cache: hot eager loops replay a cached jitted
    # scan instead of re-tracing the body per step. The token encodes every
    # closure-captured value with semantic effect; the cache's strong ref
    # to the first call's closure keeps `template` alive, so id(template)
    # cannot be reused while the entry lives.
    policy_tok = ((getattr(policy, "__name__", None), id(policy))
                  if policy is not None else None)
    # _config_sig(template) rides in the token so an IN-PLACE config edit
    # (e.g. setting every layer's dropout p) changes the key and retraces —
    # a cached trace must never replay stale config values
    # token_extra: hashable caller-supplied material for flag-dependent
    # block internals the config signature cannot see (e.g. the MoE
    # dispatch-mode kill switch — a cached trace must never replay a
    # stale dispatch path)
    token = ("scan_layers", name, id(template), num_layers, training,
             bool(use_recompute), policy_tok, len(extra), num_aux,
             token_extra, _config_sig(template))
    return apply(_scan_fn, x_t, *key_args, *flat_params, *extra, name=name,
                 _cache_token=token)


def scan_layers_with_cache(blocks, x, cache, *extra, body_call,
                           scan_in=(), name: str = "scan_layers_cache"):
    """Run ``x`` through ``blocks`` as ONE ``jax.lax.scan`` while
    threading per-layer cache state — the decode-time counterpart of
    :func:`scan_layers` (the paged-KV serving path, ISSUE 6).

    ``cache``: tuple of Tensors/arrays stacked along a leading layer
    axis (``[L, ...]`` — e.g. per-layer K/V page pools); each layer's
    slice enters the scan as a scanned-over input and the updated slice
    leaves as a scanned-over output, so the whole decode step stays one
    O(1)-trace program. ``extra``: broadcast (non-scanned) arguments
    shared by every layer (block tables, per-slot positions).

    ``body_call(template, x, cache_slices, extras)`` adapts the generic
    scan to the stack's block signature: it must run ``template`` (the
    first block, with that layer's params bound) and return
    ``(x, new_cache_slices)`` with ``new_cache_slices`` matching
    ``cache``'s structure and per-layer shapes. Pass a module-level
    function — its identity rides the eager jit-cache token.

    ``scan_in``: per-layer stacked arrays (``[L, ...]``) that scan as
    INPUTS ONLY — each layer sees its slice but no updated slice is
    carried out (the serving LoRA pools: per-layer adapter weights that
    the decode step reads but never writes). When non-empty,
    ``body_call`` is invoked with a fifth argument
    ``(template, x, cache_slices, extras, scan_in_slices)``; when empty
    the four-argument form is kept, so existing bodies are untouched.

    Eval-mode only (decode never trains): a training-mode template is
    rejected rather than silently dropping dropout randomness.

    Returns ``(y, new_cache)`` with ``new_cache`` stacked ``[L, ...]``.
    """
    blocks = list(blocks)
    template = blocks[0]
    num_layers = len(blocks)
    if bool(getattr(template, "training", False)):
        raise ValueError(
            "scan_layers_with_cache is an eval/decode path; call "
            "model.eval() first (training-mode dropout would need a "
            "per-layer RNG this cache-threading scan does not carry)")

    from ..jit.functional import bind as bind_

    names = [n for n, _ in template.named_parameters()]
    specs = {n: getattr(p, "spec", None)
             for n, p in template.named_parameters()}
    per_block = [dict(b.named_parameters()) for b in blocks]
    flat_params = [pb[n] for n in names for pb in per_block]
    n_cache = len(cache)
    n_scan_in = len(scan_in)

    SCAN_STATS["scan_calls"] += 1

    def _scan_fn(x_arr, *arrs):
        n_p = len(names) * num_layers
        p_stacked = {
            n: jnp.stack(arrs[i * num_layers:(i + 1) * num_layers], axis=0)
            for i, n in enumerate(names)}
        cache_raw = arrs[n_p:n_p + n_cache]
        scan_in_raw = arrs[n_p + n_cache:n_p + n_cache + n_scan_in]
        extra_raw = arrs[n_p + n_cache + n_scan_in:]
        # same stacked-layout TP pins as the training scan (leading layer
        # axis replicated); no-op without an active mesh
        from ..distributed.spmd import constrain
        for n in names:
            sp = specs[n]
            if sp is not None:
                p_stacked[n] = constrain(p_stacked[n], None, *tuple(sp))

        def body(carry, xs):
            SCAN_STATS["body_traces"] += 1
            p_slice, cache_slice = xs[0], xs[1]
            extras_t = tuple(Tensor(e) if hasattr(e, "dtype") else e
                             for e in extra_raw)
            with bind_(template, p_slice, None):
                if n_scan_in:
                    out, new_cache = body_call(
                        template, Tensor(carry),
                        tuple(Tensor(c) for c in cache_slice),
                        extras_t,
                        tuple(Tensor(s) for s in xs[2]))
                else:
                    out, new_cache = body_call(
                        template, Tensor(carry),
                        tuple(Tensor(c) for c in cache_slice),
                        extras_t)
            out = out._data if isinstance(out, Tensor) else out
            new_cache = tuple(c._data if isinstance(c, Tensor) else c
                              for c in new_cache)
            return out.astype(carry.dtype), new_cache

        xs = (p_stacked, tuple(cache_raw))
        if n_scan_in:
            xs = xs + (tuple(scan_in_raw),)
        y, new_cache_stacked = jax.lax.scan(body, x_arr, xs)
        return (y,) + tuple(new_cache_stacked)

    x_t = x if isinstance(x, Tensor) else Tensor(x)
    token = ("scan_layers_cache", name, id(template), num_layers, n_cache,
             n_scan_in, len(extra), id(body_call), _config_sig(template))
    out = apply(_scan_fn, x_t, *flat_params, *cache, *scan_in, *extra,
                name=name, _cache_token=token)
    return out[0], tuple(out[1:])
