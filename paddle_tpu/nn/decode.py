"""Decoding: BeamSearchDecoder + dynamic_decode + gather_tree.

reference parity: python/paddle/fluid/layers/rnn.py — Decoder(:780),
BeamSearchDecoder(:866: tile to [B*beam], log-prob accumulation, top-k
over beam*vocab, finished/eos masking), dynamic_decode(:1583: while-op
step loop), and operators/gather_tree_op.cc (parent-pointer backtrace).

TPU-native redesign: the whole decode is ONE `lax.scan` over
`max_step_num` with static shapes — no dynamic while-op, no growing
arrays. Finished beams are masked (eos forced, scores frozen) rather
than retired, which is exactly how you keep the MXU busy with a fixed
[B*beam, ...] batch; the backtrace is a reversed scan (gather_tree).
"""

from __future__ import annotations

import collections

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, apply

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode", "gather_tree"]


def gather_tree(ids, parents):
    """Backtrace beam parents into full sequences (reference:
    operators/gather_tree_op.cc). ids/parents: [T, B, beam] -> [T, B, beam].
    """

    def _gt(idarr, par):
        T = idarr.shape[0]

        def back(beam_idx, t):
            # beam_idx: [B, beam] — which beam each final path occupies
            tok = jnp.take_along_axis(idarr[t], beam_idx, axis=1)
            prev = jnp.take_along_axis(par[t], beam_idx, axis=1)
            return prev, tok

        init = jnp.broadcast_to(jnp.arange(idarr.shape[2])[None, :],
                                idarr.shape[1:])
        _, toks = lax.scan(back, init, jnp.arange(T), reverse=True)
        return toks

    return apply(_gt, ids, parents, name="gather_tree")


class Decoder:
    """Base decode contract (reference: rnn.py Decoder:780)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam search over a step cell (reference: rnn.py:866).

    cell(inputs [B*beam, ...], states) -> (cell_out, new_states);
    `output_fn(cell_out)` must produce vocab logits.
    """

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ["scores", "predicted_ids", "parent_ids"])
    StateWrapper = collections.namedtuple(
        "StateWrapper", ["cell_states", "log_probs", "finished", "lengths"])

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B*beam, ...] (for encoder outputs etc.)."""

        def _tile(a):
            return jnp.repeat(a, beam_size, axis=0)

        if isinstance(x, Tensor):
            return apply(_tile, x, name="tile_beam_merge_with_batch")
        return jax.tree_util.tree_map(_tile, x)

    def _merge(self, a):
        """[B, beam, ...] -> [B*beam, ...]"""
        return a.reshape((-1,) + a.shape[2:])

    def _split(self, a, B):
        """[B*beam, ...] -> [B, beam, ...]"""
        return a.reshape((B, self.beam_size) + a.shape[1:])

    def initialize(self, initial_cell_states):
        """Tile cell states to the beam; beam 0 active, rest -inf."""
        states = jax.tree_util.tree_map(
            lambda a: jnp.repeat(a, self.beam_size, axis=0),
            initial_cell_states)
        leaves = jax.tree_util.tree_leaves(states)
        if not leaves:
            raise ValueError(
                "BeamSearchDecoder needs initial cell states: pass "
                "dynamic_decode(decoder, inits=<cell state pytree with a "
                "[batch, ...] leading dim>, ...) — e.g. the encoder's "
                "final hidden state")
        leaf = leaves[0]
        B = leaf.shape[0] // self.beam_size
        log_probs = jnp.tile(
            jnp.array([[0.0] + [-1e9] * (self.beam_size - 1)], jnp.float32),
            (B, 1))
        finished = jnp.zeros((B, self.beam_size), bool)
        lengths = jnp.zeros((B, self.beam_size), jnp.int32)
        init_inputs = jnp.full((B * self.beam_size,), self.start_token,
                               jnp.int32)
        return init_inputs, self.StateWrapper(states, log_probs, finished,
                                              lengths), finished

    @staticmethod
    def _unwrap(tree):
        return jax.tree_util.tree_map(
            lambda x: x._data if isinstance(x, Tensor) else x, tree,
            is_leaf=lambda x: isinstance(x, Tensor))

    @staticmethod
    def _wrap(tree):
        return jax.tree_util.tree_map(
            lambda x: x if isinstance(x, Tensor) else Tensor(x), tree)

    def step(self, time, inputs, states, **kwargs):
        if self.embedding_fn is not None:
            emb = self.embedding_fn(Tensor(inputs))
            inputs = emb._data if isinstance(emb, Tensor) else emb
        cell_out, next_cell_states = self.cell(
            Tensor(inputs), self._wrap(states.cell_states), **kwargs)
        next_cell_states = self._unwrap(next_cell_states)
        if self.output_fn is not None:
            out = self.output_fn(Tensor(cell_out) if not isinstance(
                cell_out, Tensor) else cell_out)
            cell_out = out._data if isinstance(out, Tensor) else out
        elif isinstance(cell_out, Tensor):
            cell_out = cell_out._data

        V = cell_out.shape[-1]
        B = states.log_probs.shape[0]
        beam = self.beam_size
        step_lp = jax.nn.log_softmax(cell_out.astype(jnp.float32), axis=-1)
        step_lp = self._split(step_lp, B)                     # [B, bm, V]

        # finished beams only extend with eos at zero cost
        eos_only = jnp.full((V,), -1e9, jnp.float32).at[self.end_token].set(
            0.0)
        step_lp = jnp.where(states.finished[..., None], eos_only[None, None],
                            step_lp)

        total = states.log_probs[..., None] + step_lp         # [B, bm, V]
        flat = total.reshape(B, beam * V)
        top_scores, top_idx = lax.top_k(flat, beam)           # [B, beam]
        parent = (top_idx // V).astype(jnp.int32)
        token = (top_idx % V).astype(jnp.int32)

        gather = lambda a: jnp.take_along_axis(a, parent, axis=1)
        was_finished = gather(states.finished)
        finished = was_finished | (token == self.end_token)
        lengths = gather(states.lengths) + (~was_finished).astype(jnp.int32)

        # reorder cell states by parent beam
        flat_parent = (parent
                       + (jnp.arange(B) * beam)[:, None]).reshape(-1)
        next_cell_states = jax.tree_util.tree_map(
            lambda a: jnp.take(a, flat_parent, axis=0), next_cell_states)

        outputs = self.OutputWrapper(top_scores, token, parent)
        next_states = self.StateWrapper(next_cell_states, top_scores,
                                        finished, lengths)
        next_inputs = token.reshape(-1).astype(jnp.int32)
        return outputs, next_states, next_inputs, finished

    def finalize(self, outputs, final_states, sequence_lengths):
        """outputs fields stacked [T, B, beam] -> backtraced ids."""
        ids = gather_tree(Tensor(outputs.predicted_ids),
                          Tensor(outputs.parent_ids))
        return ids, final_states

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=None, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    """Run a decoder to completion (reference: rnn.py dynamic_decode:1583).

    TPU-native: one lax.scan over `max_step_num` steps (static trip count;
    finished beams are masked, not retired). Returns (outputs, final_states)
    or (outputs, final_states, sequence_lengths) when return_length=True;
    for BeamSearchDecoder `outputs` is the backtraced token tensor
    [B, T, beam] ([T, B, beam] when output_time_major).
    """
    if max_step_num is None:
        raise ValueError("max_step_num is required (static trip count "
                         "keeps the decode jittable on TPU)")
    raw_inits = jax.tree_util.tree_map(
        lambda t: t._data if isinstance(t, Tensor) else t, inits)
    init_inputs, init_states, init_finished = decoder.initialize(raw_inits)

    def scan_step(carry, t):
        inputs, states, finished = carry
        outputs, next_states, next_inputs, next_finished = decoder.step(
            t, inputs, states, **kwargs)
        next_finished = next_finished | finished if not \
            decoder.tracks_own_finished else next_finished
        return (next_inputs, next_states, next_finished), outputs

    (last_inputs, final_states, finished), stacked = lax.scan(
        scan_step, (init_inputs, init_states, init_finished),
        jnp.arange(int(max_step_num)))

    seq_len = getattr(final_states, "lengths", None)
    outputs, final_states = decoder.finalize(stacked, final_states, seq_len)
    if isinstance(outputs, Tensor):
        out = outputs
    else:
        out = Tensor(outputs)
    if not output_time_major:
        def _bt(a):
            return jnp.moveaxis(a, 0, 1)
        out = apply(_bt, out, name="dynamic_decode_transpose")
    if return_length:
        return out, final_states, Tensor(seq_len) if seq_len is not None \
            else None
    return out, final_states
