"""Cost model (reference: python/paddle/cost_model/cost_model.py —
profile_measure running a program under the profiler to collect op
costs).

TPU-native: XLA's cost analysis gives static FLOP/byte counts for the
compiled program and a timed run gives wall cost; both come from the
same jitted callable a user would train with.

This module is the ONE source of truth for program cost numbers:
:func:`normalize_cost_analysis` (shared with the per-program attribution
in ``jit/to_static.TrainStep``) and the per-chip peak-FLOPs table that
MFU math divides by (shared with ``bench.py``).
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence

__all__ = ["CostModel", "normalize_cost_analysis", "device_peak_flops",
           "PEAK_FLOPS"]

# Peak dense matmul FLOP/s per chip (bf16). f32 params are fine: the
# default matmul policy lowers f32 gemms to bf16 passes on TPU. Keys
# are matched as prefixes of jax's device_kind string.
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5": 459e12,        # v5p
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,   # v6e
}


def normalize_cost_analysis(analysis) -> Dict[str, float]:
    """Normalize ``Lowered.cost_analysis()`` output to one flat dict.

    jax returns a plain dict on current versions, but a LIST of
    per-computation dicts on some older ones (and None when the backend
    has no cost model). Numeric values of duplicate keys are summed —
    for a multi-computation program the total is what budget/MFU math
    wants. Shared by ``CostModel.profile_measure`` and the per-program
    attribution in ``TrainStep`` (one helper, both callers)."""
    if analysis is None:
        return {}
    if isinstance(analysis, (list, tuple)):
        merged: Dict[str, float] = {}
        for d in analysis:
            for k, v in (d or {}).items():
                if isinstance(v, (int, float)):
                    merged[k] = merged.get(k, 0.0) + float(v)
        return merged
    return {k: float(v) for k, v in analysis.items()
            if isinstance(v, (int, float))}


def device_peak_flops(device=None, default: Optional[float] = None) \
        -> Optional[float]:
    """Peak dense FLOP/s of ``device`` (default: first visible device)
    from :data:`PEAK_FLOPS`; ``default`` (None) when the chip is unknown
    — e.g. the CPU test backend, where an MFU number would be fiction."""
    import jax
    try:
        kind = (device or jax.devices()[0]).device_kind
    except Exception:
        return default
    for prefix, peak in PEAK_FLOPS.items():
        if kind.startswith(prefix):
            return peak
    return default


class CostModel:
    def attribute(self, lowered) -> Dict[str, float]:
        """Static cost attribution of a ``jax.stages.Lowered``:
        ``{'flops', 'bytes_accessed', 'arithmetic_intensity'}`` (zeros
        when the backend publishes no cost model). The same numbers
        ``TrainStep.stats()['programs']`` reports per program kind."""
        try:
            analysis = normalize_cost_analysis(lowered.cost_analysis())
        except Exception:
            analysis = {}
        flops = float(analysis.get("flops", 0.0))
        nbytes = float(analysis.get("bytes accessed", 0.0))
        return {"flops": flops, "bytes_accessed": nbytes,
                "arithmetic_intensity": flops / nbytes if nbytes else 0.0}

    def mfu(self, flops_per_step: float, step_seconds: float,
            device=None, peak_flops: Optional[float] = None) \
            -> Optional[float]:
        """Model-FLOPs utilization from an attributed FLOP count and a
        measured step time; None when the chip's peak is unknown."""
        peak = peak_flops if peak_flops is not None \
            else device_peak_flops(device)
        if not peak or step_seconds <= 0:
            return None
        return flops_per_step / step_seconds / peak

    def profile_measure(self, fn, args: Sequence = (), iters: int = 10,
                        warmup: int = 2) -> Dict[str, float]:
        """Measure a callable over example args.

        Returns {'flops', 'bytes_accessed', 'wall_ms', 'achieved_tflops'}.
        """
        import jax

        raw = [a._data if hasattr(a, "_data") else a for a in args]
        jitted = jax.jit(lambda *xs: fn(*xs))
        lowered = jitted.lower(*raw)
        analysis = normalize_cost_analysis(lowered.cost_analysis())
        # AOT-compile the lowering we just analyzed: the timed loop runs
        # the exact executable the numbers describe, and compilation cost
        # stays out of the warmup loop (no extra pre-warmup execution)
        compiled = lowered.compile()
        out = None
        for _ in range(max(1, warmup)):
            out = compiled(*raw)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = compiled(*raw)
        jax.block_until_ready(out)
        wall = (time.perf_counter() - t0) / iters
        flops = float(analysis.get("flops", 0.0))
        return {
            "flops": flops,
            "bytes_accessed": float(analysis.get("bytes accessed", 0.0)),
            "wall_ms": wall * 1e3,
            "achieved_tflops": flops / wall / 1e12 if wall > 0 else 0.0,
        }
