"""Cost model (reference: python/paddle/cost_model/cost_model.py —
profile_measure running a program under the profiler to collect op
costs).

TPU-native: XLA's cost analysis gives static FLOP/byte counts for the
compiled program and a timed run gives wall cost; both come from the
same jitted callable a user would train with."""

from __future__ import annotations

import time
from typing import Dict, Sequence

__all__ = ["CostModel"]


class CostModel:
    def profile_measure(self, fn, args: Sequence = (), iters: int = 10,
                        warmup: int = 2) -> Dict[str, float]:
        """Measure a callable over example args.

        Returns {'flops', 'bytes_accessed', 'wall_ms', 'achieved_tflops'}.
        """
        import jax

        raw = [a._data if hasattr(a, "_data") else a for a in args]
        jitted = jax.jit(lambda *xs: fn(*xs))
        lowered = jitted.lower(*raw)
        analysis = lowered.cost_analysis() or {}
        out = jitted(*raw)
        jax.block_until_ready(out)
        for _ in range(warmup):
            out = jitted(*raw)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = jitted(*raw)
        jax.block_until_ready(out)
        wall = (time.perf_counter() - t0) / iters
        flops = float(analysis.get("flops", 0.0))
        return {
            "flops": flops,
            "bytes_accessed": float(analysis.get("bytes accessed", 0.0)),
            "wall_ms": wall * 1e3,
            "achieved_tflops": flops / wall / 1e12 if wall > 0 else 0.0,
        }
