"""Text datasets + tokenizer (reference parity:
python/paddle/text/__init__.py; tokenizer: faster_tokenizer_op)."""

from .datasets import (Conll05st, Imdb, Imikolov, Movielens,  # noqa: F401
                       UCIHousing, WMT14, WMT16)
from .tokenizer import (BasicTokenizer, FasterTokenizer,  # noqa: F401
                        WordpieceTokenizer, load_vocab)

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16", "BasicTokenizer", "FasterTokenizer",
           "WordpieceTokenizer", "load_vocab", "viterbi_decode", "ViterbiDecoder",
]

from .viterbi import ViterbiDecoder, viterbi_decode  # noqa: E402,F401
