"""Text datasets (reference parity: python/paddle/text/__init__.py)."""

from .datasets import (Conll05st, Imdb, Imikolov, Movielens,  # noqa: F401
                       UCIHousing, WMT14, WMT16)

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16"]
