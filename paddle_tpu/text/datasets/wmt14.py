"""WMT14 en-fr translation dataset (reference parity:
text/datasets/wmt14.py — tar with src.dict/trg.dict + tab-separated
parallel text; <s>/<e>/<unk> ids 0/1/2; sequences longer than 80 dropped)."""

from __future__ import annotations

import tarfile

import numpy as np

from ._base import OfflineDataset

START, END, UNK = "<s>", "<e>", "<unk>"
UNK_IDX = 2


class WMT14(OfflineDataset):
    NAME = "wmt14"
    FILENAME = "wmt14.tgz"

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 download=True):
        mode = mode.lower()
        assert mode in ("train", "test", "gen"), mode
        assert dict_size > 0, "dict_size should be a positive number"
        self.mode = mode
        self.dict_size = dict_size
        self._path = self._resolve(data_file, download)
        self._load()

    @staticmethod
    def _to_dict(fd, size):
        out = {}
        for i, line in enumerate(fd):
            if i >= size:
                break
            out[line.decode("utf-8", "ignore").strip()] = i
        return out

    def _load(self):
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self._path) as tf:
            src_name = [m.name for m in tf if m.name.endswith("src.dict")]
            trg_name = [m.name for m in tf if m.name.endswith("trg.dict")]
            assert len(src_name) == 1 and len(trg_name) == 1
            self.src_dict = self._to_dict(tf.extractfile(src_name[0]),
                                          self.dict_size)
            self.trg_dict = self._to_dict(tf.extractfile(trg_name[0]),
                                          self.dict_size)
            suffix = f"{self.mode}/{self.mode}"
            for name in [m.name for m in tf if m.name.endswith(suffix)]:
                for raw in tf.extractfile(name):
                    parts = raw.decode("utf-8", "ignore").strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src = [self.src_dict.get(w, UNK_IDX)
                           for w in [START] + parts[0].split() + [END]]
                    trg = [self.trg_dict.get(w, UNK_IDX)
                           for w in parts[1].split()]
                    if len(src) > 80 or len(trg) > 80:
                        continue
                    self.src_ids.append(src)
                    self.trg_ids.append([self.trg_dict[START]] + trg)
                    self.trg_ids_next.append(trg + [self.trg_dict[END]])

    def get_dict(self, reverse=False):
        src, trg = self.src_dict, self.trg_dict
        if reverse:
            src = {v: k for k, v in src.items()}
            trg = {v: k for k, v in trg.items()}
        return src, trg

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)
