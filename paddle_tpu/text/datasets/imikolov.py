"""PTB language-model dataset (reference parity: text/datasets/imikolov.py).

Parses simple-examples tar (ptb.train/valid.txt): builds a min-frequency
word dict (with <s>/<e> sentence markers, <unk> last), yields NGRAM windows
or full SEQ id lists."""

from __future__ import annotations

import collections
import tarfile

import numpy as np

from ._base import OfflineDataset


class Imikolov(OfflineDataset):
    NAME = "imikolov"
    FILENAME = "simple-examples.tgz"

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=True):
        data_type = data_type.upper()
        assert data_type in ("NGRAM", "SEQ"), data_type
        mode = mode.lower()
        assert mode in ("train", "test"), mode
        self.data_type, self.mode = data_type, mode
        self.window_size = window_size
        self._path = self._resolve(data_file, download)
        self.word_idx = self._build_dict(min_word_freq)
        self._load()

    def _lines(self, split):
        name = f"./simple-examples/data/ptb.{split}.txt"
        with tarfile.open(self._path) as tf:
            f = tf.extractfile(name)
            for line in f:
                yield line.decode("utf-8", "ignore").strip().split()

    def _build_dict(self, min_freq):
        freq = collections.defaultdict(int)
        for split in ("train", "valid"):
            for words in self._lines(split):
                for w in words:
                    freq[w] += 1
                freq["<s>"] += 1
                freq["<e>"] += 1
        freq.pop("<unk>", None)
        kept = sorted(((w, c) for w, c in freq.items() if c > min_freq),
                      key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load(self):
        unk = self.word_idx["<unk>"]
        self.data = []
        split = "train" if self.mode == "train" else "valid"
        for words in self._lines(split):
            ids = [self.word_idx.get(w, unk)
                   for w in ["<s>"] + words + ["<e>"]]
            if self.data_type == "NGRAM":
                if self.window_size <= 0:
                    raise ValueError("NGRAM needs window_size > 0")
                for i in range(self.window_size, len(ids) + 1):
                    self.data.append(tuple(ids[i - self.window_size:i]))
            else:
                self.data.append(ids)

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx]) \
            if self.data_type == "NGRAM" else np.array(self.data[idx])

    def __len__(self):
        return len(self.data)
