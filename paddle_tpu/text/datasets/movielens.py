"""MovieLens ml-1m dataset (reference parity: text/datasets/movielens.py —
zip with movies.dat/users.dat/ratings.dat '::'-separated, latin encoding;
each sample = user features + movie features + [rating*2-5])."""

from __future__ import annotations

import re
import zipfile

import numpy as np

from ._base import OfflineDataset

_TITLE_RE = re.compile(r"^(.*)\((\d+)\)$")
_AGES = [1, 18, 25, 35, 45, 50, 56]


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, title_dict):
        return [
            np.array(self.index),
            np.array([categories_dict[c] for c in self.categories]),
            np.array([title_dict[w.lower()] for w in self.title.split()]),
        ]


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.gender = gender == "M"
        self.age = _AGES.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [np.array(self.index), np.array(int(self.gender)),
                np.array(self.age), np.array(self.job_id)]


class Movielens(OfflineDataset):
    NAME = "sentiment"          # reference caches under 'sentiment'
    FILENAME = "ml-1m.zip"

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        mode = mode.lower()
        assert mode in ("train", "test"), mode
        self.mode = mode
        self.test_ratio = test_ratio
        self._path = self._resolve(data_file, download)
        np.random.seed(rand_seed)
        self._load_meta()
        self._load_ratings()

    def _load_meta(self):
        self.movie_info, self.user_info = {}, {}
        self.movie_title_dict, self.categories_dict = {}, {}
        titles, cats = set(), set()
        with zipfile.ZipFile(self._path) as z:
            with z.open("ml-1m/movies.dat") as f:
                for line in f:
                    mid, title, categories = line.decode(
                        "latin-1").strip().split("::")
                    categories = categories.split("|")
                    cats.update(categories)
                    title = _TITLE_RE.match(title).group(1)
                    self.movie_info[int(mid)] = MovieInfo(
                        mid, categories, title)
                    titles.update(w.lower() for w in title.split())
            with z.open("ml-1m/users.dat") as f:
                for line in f:
                    uid, gender, age, job, _ = line.decode(
                        "latin-1").strip().split("::")
                    self.user_info[int(uid)] = UserInfo(uid, gender, age,
                                                        job)
        self.movie_title_dict = {w: i for i, w in enumerate(titles)}
        self.categories_dict = {c: i for i, c in enumerate(cats)}

    def _load_ratings(self):
        self.data = []
        is_test = self.mode == "test"
        with zipfile.ZipFile(self._path) as z:
            with z.open("ml-1m/ratings.dat") as f:
                for line in f:
                    if (np.random.random() < self.test_ratio) != is_test:
                        continue
                    uid, mid, rating, _ = line.decode(
                        "latin-1").strip().split("::")
                    usr = self.user_info[int(uid)]
                    mov = self.movie_info[int(mid)]
                    self.data.append(
                        usr.value()
                        + mov.value(self.categories_dict,
                                    self.movie_title_dict)
                        + [np.array([float(rating) * 2 - 5.0])])

    def __getitem__(self, idx):
        return tuple(self.data[idx])

    def __len__(self):
        return len(self.data)
