"""CoNLL-2005 SRL dataset (reference parity: text/datasets/conll05.py —
test.wsj words/props gz files inside the release tar, external
word/verb/target dicts; samples are the standard 9-field SRL encoding:
words, 5 verb-context windows, predicate, mark, BIO labels)."""

from __future__ import annotations

import gzip
import os
import tarfile

import numpy as np

from ._base import DATA_HOME, OfflineDataset

UNK_IDX = 0


class Conll05st(OfflineDataset):
    NAME = "conll05st"
    FILENAME = "conll05st-tests.tar.gz"

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, emb_file=None,
                 download=True):
        self._path = self._resolve(data_file, download)
        home = os.path.join(DATA_HOME, self.NAME)
        self.word_dict_file = word_dict_file or os.path.join(
            home, "wordDict.txt")
        self.verb_dict_file = verb_dict_file or os.path.join(
            home, "verbDict.txt")
        self.target_dict_file = target_dict_file or os.path.join(
            home, "targetDict.txt")
        self.emb_file = emb_file or os.path.join(home, "emb")
        for f in (self.word_dict_file, self.verb_dict_file,
                  self.target_dict_file):
            if not os.path.exists(f):
                raise RuntimeError(
                    f"Conll05st: dictionary {f} missing; no egress to fetch "
                    "it — pass *_dict_file paths explicitly")
        self.word_dict = self._load_dict(self.word_dict_file)
        self.predicate_dict = self._load_dict(self.verb_dict_file)
        self.label_dict = self._load_label_dict(self.target_dict_file)
        self._load_anno()

    @staticmethod
    def _load_dict(path):
        with open(path) as f:
            return {line.strip(): i for i, line in enumerate(f)}

    @staticmethod
    def _load_label_dict(path):
        tags = set()
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line.startswith(("B-", "I-")):
                    tags.add(line[2:])
        d = {}
        for tag in tags:
            d["B-" + tag] = len(d)
            d["I-" + tag] = len(d)
        d["O"] = len(d)
        return d

    @staticmethod
    def _expand_props(prop_cols):
        """One proposition column of CoNLL star notation -> BIO tags."""
        seq = []
        cur, inside = "O", False
        for tok in prop_cols:
            if tok == "*":
                seq.append("I-" + cur if inside else "O")
            elif tok == "*)":
                seq.append("I-" + cur)
                inside = False
            elif "(" in tok and ")" in tok:
                cur = tok[1:tok.find("*")]
                seq.append("B-" + cur)
                inside = False
            elif "(" in tok:
                cur = tok[1:tok.find("*")]
                seq.append("B-" + cur)
                inside = True
            else:
                raise RuntimeError(f"Unexpected label: {tok}")
        return seq

    def _load_anno(self):
        self.sentences, self.predicates, self.labels = [], [], []
        with tarfile.open(self._path) as tf:
            wf = tf.extractfile(
                "conll05st-release/test.wsj/words/test.wsj.words.gz")
            pf = tf.extractfile(
                "conll05st-release/test.wsj/props/test.wsj.props.gz")
            with gzip.GzipFile(fileobj=wf) as words, \
                    gzip.GzipFile(fileobj=pf) as props:
                sent, cols = [], []
                for wline, pline in zip(words, props):
                    word = wline.decode("utf-8", "ignore").strip()
                    fields = pline.decode("utf-8", "ignore").strip().split()
                    if not fields:                     # sentence boundary
                        if cols:
                            verbs = [v for v in cols[0] if v != "-"]
                            for i in range(1, len(cols)):
                                self.sentences.append(sent)
                                self.predicates.append(verbs[i - 1])
                                self.labels.append(
                                    self._expand_props(cols[i]))
                        sent, cols = [], []
                        continue
                    sent = sent + [word] if sent else [word]
                    if not cols:
                        cols = [[] for _ in fields]
                    for i, fld in enumerate(fields):
                        cols[i].append(fld)

    def __getitem__(self, idx):
        sentence, predicate = self.sentences[idx], self.predicates[idx]
        labels = self.labels[idx]
        n = len(sentence)
        v = labels.index("B-V")
        mark = [0] * n
        ctx = {}
        for off, name, fallback in ((-2, "n2", "bos"), (-1, "n1", "bos"),
                                    (0, "0", None), (1, "p1", "eos"),
                                    (2, "p2", "eos")):
            j = v + off
            if 0 <= j < n:
                mark[j] = 1
                ctx[name] = sentence[j]
            else:
                ctx[name] = fallback
        wd = self.word_dict
        word_idx = [wd.get(w, UNK_IDX) for w in sentence]
        rows = [np.array(word_idx)]
        for name in ("n2", "n1", "0", "p1", "p2"):
            rows.append(np.array([wd.get(ctx[name], UNK_IDX)] * n))
        rows.append(np.array([self.predicate_dict.get(predicate)] * n))
        rows.append(np.array(mark))
        rows.append(np.array([self.label_dict.get(t) for t in labels]))
        return tuple(rows)

    def __len__(self):
        return len(self.sentences)

    def get_dict(self):
        return self.word_dict, self.predicate_dict, self.label_dict

    def get_embedding(self):
        if not os.path.exists(self.emb_file):
            raise RuntimeError(f"embedding file {self.emb_file} missing")
        return np.loadtxt(self.emb_file, dtype=np.float32)
