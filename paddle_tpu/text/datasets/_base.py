"""Shared plumbing for text datasets.

reference parity: each dataset in python/paddle/text/datasets/ downloads
its archive via paddle.dataset.common.DOWNLOAD_HOME and parses it lazily.
This environment has no egress, so ``download=True`` without a local file
raises with the expected path instead of fetching; parsing logic accepts
the same archive formats the reference downloads.
"""

from __future__ import annotations

import os

from ...io.dataset import Dataset

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


class OfflineDataset(Dataset):
    """Dataset resolved from a local file; no network access."""

    NAME = "dataset"
    FILENAME = "data"

    def _resolve(self, data_file, download):
        if data_file:
            if not os.path.exists(data_file):
                raise FileNotFoundError(data_file)
            return data_file
        cached = os.path.join(DATA_HOME, self.NAME, self.FILENAME)
        if os.path.exists(cached):
            return cached
        raise RuntimeError(
            f"{type(self).__name__}: no network egress is available; place "
            f"the archive at {cached} or pass data_file= explicitly "
            f"(reference downloads it from the paddle dataset mirror)")
