"""WMT16 en-de translation dataset (reference parity:
text/datasets/wmt16.py — tar with per-language vocab files built on first
use, <s>/<e>/<unk> ids 0/1/2, lowercase tokenization)."""

from __future__ import annotations

import collections
import os
import tarfile

import numpy as np

from ._base import DATA_HOME, OfflineDataset

START_MARK, END_MARK, UNK_MARK = "<s>", "<e>", "<unk>"


class WMT16(OfflineDataset):
    NAME = "wmt16"
    FILENAME = "wmt16.tar.gz"

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=True):
        mode = mode.lower()
        assert mode in ("train", "test", "val"), mode
        assert lang in ("en", "de"), lang
        assert src_dict_size > 0 and trg_dict_size > 0, \
            "dict sizes should be positive numbers"
        self.mode = mode
        self.lang = lang
        self._path = self._resolve(data_file, download)
        self.src_dict_size = min(src_dict_size, self._vocab_limit(lang))
        trg_lang = "de" if lang == "en" else "en"
        self.trg_dict_size = min(trg_dict_size, self._vocab_limit(trg_lang))
        self.src_dict = self._load_dict(lang, self.src_dict_size)
        self.trg_dict = self._load_dict(trg_lang, self.trg_dict_size)
        self._load_data(trg_lang)

    def _vocab_limit(self, lang):
        return 10**9

    def _dict_path(self, lang, size):
        return os.path.join(DATA_HOME, self.NAME,
                            f"wmt16.{lang}.dict.{size}")

    def _load_dict(self, lang, size):
        path = self._dict_path(lang, size)
        if not os.path.exists(path):
            self._build_dict(path, lang, size)
        out = {}
        with open(path, "rb") as f:
            for i, line in enumerate(f):
                out[line.decode("utf-8", "ignore").strip()] = i
        return out

    def _build_dict(self, path, lang, size):
        freq = collections.defaultdict(int)
        with tarfile.open(self._path) as tf:
            f = tf.extractfile(f"wmt16/train")
            col = 0 if lang == self.lang else 1
            for raw in f:
                parts = raw.decode("utf-8", "ignore").strip().split("\t")
                if len(parts) != 2:
                    continue
                for w in parts[col].split():
                    freq[w] += 1
        words = [w for w, _ in sorted(freq.items(),
                                      key=lambda x: (-x[1], x[0]))]
        words = [START_MARK, END_MARK, UNK_MARK] + words[:max(0, size - 3)]
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            f.write("\n".join(words) + "\n")

    def _load_data(self, trg_lang):
        unk = self.src_dict.get(UNK_MARK, 2)
        unk_t = self.trg_dict.get(UNK_MARK, 2)
        s0, e0 = self.src_dict[START_MARK], self.src_dict[END_MARK]
        s1, e1 = self.trg_dict[START_MARK], self.trg_dict[END_MARK]
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self._path) as tf:
            f = tf.extractfile(f"wmt16/{self.mode}")
            for raw in f:
                parts = raw.decode("utf-8", "ignore").strip().split("\t")
                if len(parts) != 2:
                    continue
                src = [s0] + [self.src_dict.get(w, unk)
                              for w in parts[0].split()] + [e0]
                trg_words = [self.trg_dict.get(w, unk_t)
                             for w in parts[1].split()]
                self.src_ids.append(src)
                self.trg_ids.append([s1] + trg_words)
                self.trg_ids_next.append(trg_words + [e1])

    def get_dict(self, lang, reverse=False):
        d = self.src_dict if lang == self.lang else self.trg_dict
        return {v: k for k, v in d.items()} if reverse else d

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)
