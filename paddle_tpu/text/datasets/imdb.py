"""IMDB sentiment dataset (reference parity: text/datasets/imdb.py).

Parses the aclImdb tar: builds a frequency-cutoff word dict over
train+test pos/neg docs (punctuation stripped, lowercased), then encodes
the requested split. Label 0 = positive, 1 = negative (reference order)."""

from __future__ import annotations

import collections
import re
import string
import tarfile

import numpy as np

from ._base import OfflineDataset

_PUNCT = str.maketrans("", "", string.punctuation)


class Imdb(OfflineDataset):
    NAME = "imdb"
    FILENAME = "aclImdb_v1.tar.gz"

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        mode = mode.lower()
        assert mode in ("train", "test"), mode
        self.mode = mode
        self._path = self._resolve(data_file, download)
        self.word_idx = self._build_dict(cutoff)
        self._encode()

    def _docs(self, pattern):
        rx = re.compile(pattern)
        with tarfile.open(self._path) as tf:
            for m in tf:
                if m.isfile() and rx.match(m.name):
                    text = tf.extractfile(m).read().decode(
                        "utf-8", "ignore").rstrip("\n\r")
                    yield text.translate(_PUNCT).lower().split()

    def _build_dict(self, cutoff):
        freq = collections.defaultdict(int)
        for doc in self._docs(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$"):
            for w in doc:
                freq[w] += 1
        kept = sorted(((w, c) for w, c in freq.items() if c > cutoff),
                      key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _encode(self):
        unk = self.word_idx["<unk>"]
        self.docs, self.labels = [], []
        for label, sub in ((0, "pos"), (1, "neg")):
            for doc in self._docs(rf"aclImdb/{self.mode}/{sub}/.*\.txt$"):
                self.docs.append([self.word_idx.get(w, unk) for w in doc])
                self.labels.append(label)

    def __getitem__(self, idx):
        return np.array(self.docs[idx]), np.array([self.labels[idx]])

    def __len__(self):
        return len(self.docs)
