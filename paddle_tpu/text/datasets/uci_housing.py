"""UCI housing dataset (reference parity: text/datasets/uci_housing.py).

Parses the whitespace-delimited housing.data file: 13 features + target,
features min/max-normalized over the WHOLE corpus, first 80% train /
last 20% test (the reference's split)."""

from __future__ import annotations

import numpy as np

from ._base import OfflineDataset

feature_names = ['CRIM', 'ZN', 'INDUS', 'CHAS', 'NOX', 'RM', 'AGE', 'DIS',
                 'RAD', 'TAX', 'PTRATIO', 'B', 'LSTAT']


class UCIHousing(OfflineDataset):
    NAME = "uci_housing"
    FILENAME = "housing.data"

    def __init__(self, data_file=None, mode="train", download=True):
        mode = mode.lower()
        assert mode in ("train", "test"), mode
        self.mode = mode
        path = self._resolve(data_file, download)
        raw = np.loadtxt(path).astype(np.float32)
        if raw.shape[1] != 14:
            raise ValueError(f"expected 14 columns, got {raw.shape[1]}")
        feats, target = raw[:, :13], raw[:, 13:]
        lo, hi = feats.min(axis=0), feats.max(axis=0)
        avg = feats.mean(axis=0)
        feats = (feats - avg) / np.where(hi - lo == 0, 1, hi - lo)
        split = int(len(raw) * 0.8)
        if mode == "train":
            self.data = np.concatenate([feats[:split], target[:split]], 1)
        else:
            self.data = np.concatenate([feats[split:], target[split:]], 1)

    def __getitem__(self, idx):
        row = self.data[idx]
        return np.asarray(row[:13]), np.asarray(row[13:])

    def __len__(self):
        return len(self.data)
