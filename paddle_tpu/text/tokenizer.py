"""BERT wordpiece tokenizer — host-side, fixed-shape, jit-ready output.

reference parity: paddle/fluid/operators/string/faster_tokenizer_op.h —
BasicTokenizer(:46), WordPieceTokenizer(:57), BertTokenizer(:71) with
BatchEncode(:97); exposed in the reference as the faster_tokenizer op
taking string tensors.

TPU-native design: strings never touch the device. Tokenization runs on
host CPU (the one place it can), and the tokenizer emits PADDED,
FIXED-SHAPE int32 arrays (input_ids, token_type_ids, attention mask) so
every batch hits the same compiled executable — the XLA analogue of the
reference fusing tokenization into the graph. Drop the output straight
into a jitted TrainStep or the DataLoader's collate path.
"""

from __future__ import annotations

import unicodedata
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

__all__ = ["BasicTokenizer", "WordpieceTokenizer", "FasterTokenizer",
           "load_vocab"]


def load_vocab(path: str) -> Dict[str, int]:
    """One token per line -> {token: index} (BERT vocab.txt format)."""
    vocab: Dict[str, int] = {}
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            tok = line.rstrip("\n")
            if tok:
                vocab[tok] = i
    return vocab


def _is_whitespace(ch: str) -> bool:
    return ch in " \t\n\r" or unicodedata.category(ch) == "Zs"


def _is_control(ch: str) -> bool:
    if ch in "\t\n\r":
        return False
    return unicodedata.category(ch).startswith("C")


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    # ASCII ranges treated as punctuation even when unicode disagrees
    # (e.g. '$', '`'): the BERT convention
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp: int) -> bool:
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
            or 0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF
            or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F)


class BasicTokenizer:
    """Whitespace/punctuation/CJK splitting + optional lowercasing with
    accent stripping (reference: faster_tokenizer_op.h:46)."""

    def __init__(self, do_lower_case: bool = True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text: str) -> List[str]:
        # strip control chars, normalize whitespace, space out CJK
        chars = []
        for ch in text:
            cp = ord(ch)
            if cp == 0 or cp == 0xFFFD or _is_control(ch):
                continue
            if _is_cjk(cp):
                chars.extend((" ", ch, " "))
            elif _is_whitespace(ch):
                chars.append(" ")
            else:
                chars.append(ch)
        tokens = []
        for word in "".join(chars).split():
            if self.do_lower_case:
                word = word.lower()
                word = "".join(c for c in unicodedata.normalize("NFD", word)
                               if unicodedata.category(c) != "Mn")
            # split on punctuation
            cur: List[str] = []
            for ch in word:
                if _is_punctuation(ch):
                    if cur:
                        tokens.append("".join(cur))
                        cur = []
                    tokens.append(ch)
                else:
                    cur.append(ch)
            if cur:
                tokens.append("".join(cur))
        return tokens


class WordpieceTokenizer:
    """Greedy longest-match-first subword split (reference:
    faster_tokenizer_op.h:57)."""

    def __init__(self, vocab: Dict[str, int], unk_token: str = "[UNK]",
                 max_input_chars_per_word: int = 100):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_input_chars_per_word = max_input_chars_per_word

    def tokenize(self, word: str) -> List[str]:
        if len(word) > self.max_input_chars_per_word:
            return [self.unk_token]
        out: List[str] = []
        start = 0
        while start < len(word):
            end = len(word)
            piece = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    piece = sub
                    break
                end -= 1
            if piece is None:
                return [self.unk_token]
            out.append(piece)
            start = end
        return out


class FasterTokenizer:
    """BERT tokenizer emitting fixed-shape, padded int32 batches.

    reference: faster_tokenizer_op.h BertTokenizer(:71) — the in-graph
    string op; here a host-side callable whose output arrays feed jit
    directly. Accepts a vocab dict or a vocab.txt path.

    Call with a string / list of strings (and optional ``text_pair``);
    returns a dict of numpy int32 arrays ``input_ids``,
    ``token_type_ids`` and float32 ``attention_mask`` shaped
    [batch, max_seq_len].
    """

    def __init__(self, vocab: Union[Dict[str, int], str],
                 do_lower_case: bool = True, unk_token: str = "[UNK]",
                 sep_token: str = "[SEP]", pad_token: str = "[PAD]",
                 cls_token: str = "[CLS]", mask_token: str = "[MASK]"):
        self.vocab = (load_vocab(vocab) if isinstance(vocab, str)
                      else dict(vocab))
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordpieceTokenizer(self.vocab, unk_token)
        self.unk_token, self.sep_token = unk_token, sep_token
        self.pad_token, self.cls_token = pad_token, cls_token
        self.mask_token = mask_token
        for tok in (unk_token, sep_token, pad_token, cls_token):
            if tok not in self.vocab:
                raise ValueError(f"special token {tok!r} not in vocab")
        self.pad_id = self.vocab[pad_token]
        self.cls_id = self.vocab[cls_token]
        self.sep_id = self.vocab[sep_token]

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    def tokenize(self, text: str) -> List[str]:
        return [p for w in self.basic.tokenize(text)
                for p in self.wordpiece.tokenize(w)]

    def convert_tokens_to_ids(self, tokens: Sequence[str]) -> List[int]:
        unk = self.vocab[self.unk_token]
        return [self.vocab.get(t, unk) for t in tokens]

    def _encode_one(self, text: str, pair: Optional[str],
                    max_seq_len: int) -> Tuple[List[int], List[int]]:
        ids = self.convert_tokens_to_ids(self.tokenize(text))
        pair_ids = (self.convert_tokens_to_ids(self.tokenize(pair))
                    if pair is not None else None)
        # truncate longest-first to fit specials (reference:
        # TruncateSequence, :89)
        n_special = 3 if pair_ids is not None else 2
        if pair_ids is None:
            ids = ids[:max_seq_len - n_special]
        else:
            while len(ids) + len(pair_ids) > max_seq_len - n_special:
                if len(ids) >= len(pair_ids):
                    ids.pop()
                else:
                    pair_ids.pop()
        out = [self.cls_id] + ids + [self.sep_id]
        types = [0] * len(out)
        if pair_ids is not None:
            out += pair_ids + [self.sep_id]
            types += [1] * (len(pair_ids) + 1)
        return out, types

    def __call__(self, text: Union[str, Sequence[str]],
                 text_pair: Optional[Union[str, Sequence[str]]] = None,
                 max_seq_len: int = 128,
                 pad_to_max_seq_len: bool = True) -> Dict[str, np.ndarray]:
        texts = [text] if isinstance(text, str) else list(text)
        pairs: List[Optional[str]]
        if text_pair is None:
            pairs = [None] * len(texts)
        else:
            pairs = ([text_pair] if isinstance(text_pair, str)
                     else list(text_pair))
        if len(pairs) != len(texts):
            raise ValueError("text_pair batch size mismatch")

        encoded = [self._encode_one(t, p, max_seq_len)
                   for t, p in zip(texts, pairs)]
        width = (max_seq_len if pad_to_max_seq_len
                 else max(len(ids) for ids, _ in encoded))
        input_ids = np.full((len(texts), width), self.pad_id, np.int32)
        token_type = np.zeros((len(texts), width), np.int32)
        mask = np.zeros((len(texts), width), np.float32)
        for i, (ids, types) in enumerate(encoded):
            input_ids[i, :len(ids)] = ids
            token_type[i, :len(types)] = types
            mask[i, :len(ids)] = 1.0
        return {"input_ids": input_ids, "token_type_ids": token_type,
                "attention_mask": mask}
