"""Viterbi decoding (reference: python/paddle/text/viterbi_decode.py,
paddle/fluid/operators/viterbi_decode_op.h).

Semantics follow the reference op: `transitions` is [C, C]; with
include_bos_eos_tag=True the last row is the start-tag transition
(added to step 0) and the second-to-last row is the stop-tag transition
(added at each sequence's final valid step) — the row split the kernel
performs at viterbi_decode_op.h:319-338.

TPU-native: the whole decode is one `lax.scan` forward (max-product with
stored backpointers, length-masked carries) plus one reversed scan for
the backtrace — static shapes, fully jittable, batched over B on the VPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, apply
from ..nn.layer import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def _viterbi(emissions, trans, lengths, include_bos_eos_tag):
    B, L, C = emissions.shape
    lengths = lengths.astype(jnp.int32)
    if include_bos_eos_tag:
        start_row = trans[C - 1]            # start -> tag
        stop_row = trans[C - 2]             # stop-tag row (kernel's split)
    else:
        start_row = jnp.zeros((C,), trans.dtype)
        stop_row = jnp.zeros((C,), trans.dtype)

    alpha0 = emissions[:, 0, :] + start_row[None, :]
    # a length-1 sequence stops immediately
    alpha0 = alpha0 + jnp.where((lengths == 1)[:, None], stop_row[None, :],
                                0.0)

    def step(alpha, inp):
        emit_t, t = inp                      # emit_t: [B, C]
        # scores[i, j] = alpha[i] + trans[i, j]
        scores = alpha[:, :, None] + trans[None, :, :]
        best_prev = jnp.argmax(scores, axis=1)            # [B, C]
        alpha_new = jnp.max(scores, axis=1) + emit_t
        alpha_new = alpha_new + jnp.where(
            (lengths == t + 1)[:, None], stop_row[None, :], 0.0)
        active = (t < lengths)[:, None]
        alpha = jnp.where(active, alpha_new, alpha)
        # frozen steps keep the identity backpointer so the backtrace
        # passes through them untouched
        bp = jnp.where(active, best_prev,
                       jnp.arange(C, dtype=best_prev.dtype)[None, :])
        return alpha, bp

    ts = jnp.arange(1, L, dtype=jnp.int32)
    alpha, bps = lax.scan(step, alpha0,
                          (jnp.moveaxis(emissions[:, 1:, :], 1, 0), ts))
    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1).astype(jnp.int32)   # [B]

    def back(tag, bp):
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev.astype(jnp.int32), tag

    first_tag, rev_path = lax.scan(back, last_tag, bps, reverse=True)
    path = jnp.concatenate([first_tag[None], rev_path], axis=0)   # [L, B]
    path = jnp.moveaxis(path, 0, 1).astype(jnp.int64)             # [B, L]
    # positions at/after each length are padding: zero them
    mask = jnp.arange(L)[None, :] < lengths[:, None]
    return scores, jnp.where(mask, path, 0)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Highest-scoring tag path (reference: text/viterbi_decode.py).

    potentials [B, L, C] float; transition_params [C, C]; lengths [B]
    int64. Returns (scores [B], paths [B, L] int64)."""

    def _vd(e, t, ln):
        return _viterbi(e, t, ln, include_bos_eos_tag)

    return apply(_vd, potentials, transition_params, lengths,
                 name="viterbi_decode")


class ViterbiDecoder(Layer):
    """Decoder layer holding the flag (reference: ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
