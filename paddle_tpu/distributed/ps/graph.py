"""Graph table + service: the PS stack's graph-learning tail.

reference parity: paddle/fluid/distributed/table/common_graph_table.h:1
(GraphTable: typed nodes/edges, neighbor sampling, node features) and
service/graph_brpc_server.cc (the brpc service exposing it to trainers
for GNN pipelines).

TPU-native redesign: graph sampling is HOST work feeding device batches
— the table lives in host RAM as CSR adjacency per edge type (numpy,
vectorized sampling) and serves either in-process (the usual pod
layout: every worker's host holds a shard) or over the same
length-prefixed TCP framing the C++ parameter server uses
(`GraphService`/`GraphClient`, python — the hot path of a GNN step is
the sampler, which is numpy-vectorized; the dense/sparse parameter
traffic stays on the C++ server).
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["GraphTable", "GraphService", "GraphClient"]


class GraphTable:
    """Typed graph in host memory (reference: common_graph_table.h).

    Edges are grouped by ``edge_type``; ``build()`` freezes them into CSR
    for vectorized neighbor sampling. Node features are named dense
    arrays keyed by node id.
    """

    def __init__(self, seed: int = 0):
        self._pending: Dict[str, List] = {}
        self._csr: Dict[str, Tuple[np.ndarray, np.ndarray, np.ndarray]] = {}
        self._nodes: Dict[str, np.ndarray] = {}      # node_type -> ids
        self._feats: Dict[str, Dict[str, np.ndarray]] = {}  # name->{id->row}
        self._feat_store: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        self._rng = np.random.default_rng(seed)

    # -- construction ------------------------------------------------------
    def add_graph_node(self, node_type: str, ids) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        prev = self._nodes.get(node_type)
        self._nodes[node_type] = ids if prev is None else \
            np.unique(np.concatenate([prev, ids]))

    def add_edges(self, edge_type: str, src, dst) -> None:
        src = np.asarray(src, np.int64).reshape(-1)
        dst = np.asarray(dst, np.int64).reshape(-1)
        if len(src) != len(dst):
            raise ValueError("src/dst length mismatch")
        self._pending.setdefault(edge_type, []).append((src, dst))
        self._csr.pop(edge_type, None)       # invalidate built form

    def set_node_feat(self, feat_name: str, ids, rows) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        rows = np.asarray(rows, np.float32)
        rows = rows.reshape(len(ids), -1)
        old_ids, old_rows = self._feat_store.get(
            feat_name, (np.empty(0, np.int64),
                        np.empty((0, rows.shape[1]), np.float32)))
        keep = ~np.isin(old_ids, ids)
        merged_ids = np.concatenate([old_ids[keep], ids])
        merged_rows = np.concatenate([old_rows[keep], rows])
        order = np.argsort(merged_ids)      # get_node_feat searchsorts
        self._feat_store[feat_name] = (merged_ids[order],
                                       merged_rows[order])

    def build(self) -> None:
        """Freeze pending edges into CSR (reference: build_sampler)."""
        for et, chunks in self._pending.items():
            if et in self._csr:
                continue
            src = np.concatenate([s for s, _ in chunks])
            dst = np.concatenate([d for _, d in chunks])
            order = np.argsort(src, kind="stable")
            src, dst = src[order], dst[order]
            uniq, starts = np.unique(src, return_index=True)
            indptr = np.append(starts, len(src))
            self._csr[et] = (uniq, indptr, dst)

    # -- queries -----------------------------------------------------------
    def _adj(self, edge_type: str):
        if edge_type not in self._csr:
            self.build()
        if edge_type not in self._csr:
            raise KeyError(f"no edges of type {edge_type!r}")
        return self._csr[edge_type]

    def sample_neighbors(self, edge_type: str, ids, sample_size: int,
                         replace: bool = False
                         ) -> Tuple[np.ndarray, np.ndarray]:
        """Up to ``sample_size`` neighbors per id (reference:
        graph_brpc_server sample_neighbors). Returns (flat_neighbors,
        counts) — counts[i] neighbors for ids[i], concatenated."""
        uniq, indptr, dst = self._adj(edge_type)
        ids = np.asarray(ids, np.int64).reshape(-1)
        pos = np.searchsorted(uniq, ids)
        found = (pos < len(uniq)) & (uniq[np.minimum(pos, len(uniq) - 1)]
                                     == ids)
        out: List[np.ndarray] = []
        counts = np.zeros(len(ids), np.int64)
        for i, (p, ok) in enumerate(zip(pos, found)):
            if not ok:
                continue
            nbrs = dst[indptr[p]:indptr[p + 1]]
            if len(nbrs) > sample_size and not replace:
                nbrs = self._rng.choice(nbrs, sample_size, replace=False)
            elif replace:
                nbrs = self._rng.choice(nbrs, sample_size, replace=True)
            counts[i] = len(nbrs)
            out.append(nbrs)
        flat = np.concatenate(out) if out else np.empty(0, np.int64)
        return flat, counts

    def random_sample_nodes(self, node_type: str,
                            sample_size: int) -> np.ndarray:
        ids = self._nodes.get(node_type)
        if ids is None or not len(ids):
            return np.empty(0, np.int64)
        k = min(sample_size, len(ids))
        return self._rng.choice(ids, k, replace=False)

    def get_node_feat(self, feat_name: str, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        fid, rows = self._feat_store.get(
            feat_name, (np.empty(0, np.int64),
                        np.empty((0, 0), np.float32)))
        dim = rows.shape[1] if rows.size else 0
        out = np.zeros((len(ids), dim), np.float32)
        pos = np.searchsorted(fid, ids)
        ok = (pos < len(fid)) & (fid[np.minimum(pos, len(fid) - 1)] == ids)
        out[ok] = rows[pos[ok]]
        return out

    def degree(self, edge_type: str, ids) -> np.ndarray:
        uniq, indptr, _ = self._adj(edge_type)
        ids = np.asarray(ids, np.int64).reshape(-1)
        pos = np.searchsorted(uniq, ids)
        ok = (pos < len(uniq)) & (uniq[np.minimum(pos, len(uniq) - 1)]
                                  == ids)
        deg = np.zeros(len(ids), np.int64)
        deg[ok] = indptr[pos[ok] + 1] - indptr[pos[ok]]
        return deg

    # -- checkpoint --------------------------------------------------------
    def save(self, dirname: str) -> None:
        os.makedirs(dirname, exist_ok=True)
        self.build()
        state = {"csr": self._csr, "nodes": self._nodes,
                 "feats": self._feat_store}
        with open(os.path.join(dirname, "graph_table.pkl"), "wb") as f:
            pickle.dump(state, f)

    def load(self, dirname: str) -> None:
        with open(os.path.join(dirname, "graph_table.pkl"), "rb") as f:
            state = pickle.load(f)
        self._csr = state["csr"]
        self._nodes = state["nodes"]
        self._feat_store = state["feats"]
        self._pending = {}


# ---------------------------------------------------------------------------
# TCP service (reference: graph_brpc_server.cc) — same length-prefixed
# framing family as the C++ parameter server.
# ---------------------------------------------------------------------------

def _send_msg(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_msg(sock: socket.socket) -> bytes:
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("graph service closed")
        hdr += chunk
    n = struct.unpack("<Q", hdr)[0]
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("graph service closed")
        buf += chunk
    return bytes(buf)


class GraphService:
    """Serve a GraphTable over TCP (threaded; sampling is numpy work that
    releases the GIL in the hot loops)."""

    def __init__(self, table: GraphTable, host: str = "127.0.0.1",
                 port: int = 0):
        self.table = table
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.endpoint = "%s:%d" % self._srv.getsockname()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        while not self._stop.is_set():
            try:
                self._srv.settimeout(0.2)
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._client_loop, args=(conn,),
                             daemon=True).start()

    def _client_loop(self, conn):
        try:
            while True:
                req = pickle.loads(_recv_msg(conn))
                op = req.pop("op")
                if op == "stop":
                    _send_msg(conn, pickle.dumps({"ok": True}))
                    return
                try:
                    fn = getattr(self.table, op)
                    out = fn(**req)
                    _send_msg(conn, pickle.dumps({"ok": True,
                                                  "result": out}))
                except Exception as e:            # report, keep serving
                    _send_msg(conn, pickle.dumps({"ok": False,
                                                  "error": repr(e)}))
        except (ConnectionError, EOFError):
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass


class GraphClient:
    """Remote GraphTable with the SAME method surface (reference:
    GraphBrpcClient)."""

    def __init__(self, endpoint: str):
        host, port = endpoint.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)), timeout=30)
        self._lock = threading.Lock()

    def _call(self, op: str, **kw):
        with self._lock:
            _send_msg(self._sock, pickle.dumps({"op": op, **kw}))
            resp = pickle.loads(_recv_msg(self._sock))
        if not resp.get("ok"):
            raise RuntimeError(f"graph service error: {resp.get('error')}")
        return resp.get("result")

    def add_graph_node(self, node_type, ids):
        return self._call("add_graph_node", node_type=node_type, ids=ids)

    def add_edges(self, edge_type, src, dst):
        return self._call("add_edges", edge_type=edge_type, src=src,
                          dst=dst)

    def set_node_feat(self, feat_name, ids, rows):
        return self._call("set_node_feat", feat_name=feat_name, ids=ids,
                          rows=rows)

    def build(self):
        return self._call("build")

    def sample_neighbors(self, edge_type, ids, sample_size,
                         replace=False):
        return self._call("sample_neighbors", edge_type=edge_type,
                          ids=ids, sample_size=sample_size,
                          replace=replace)

    def random_sample_nodes(self, node_type, sample_size):
        return self._call("random_sample_nodes", node_type=node_type,
                          sample_size=sample_size)

    def get_node_feat(self, feat_name, ids):
        return self._call("get_node_feat", feat_name=feat_name, ids=ids)

    def degree(self, edge_type, ids):
        return self._call("degree", edge_type=edge_type, ids=ids)

    def close(self):
        try:
            self._call("stop")
        except Exception:
            pass
        self._sock.close()
