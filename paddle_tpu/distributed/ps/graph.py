"""Graph table + service: the PS stack's graph-learning tail.

reference parity: paddle/fluid/distributed/table/common_graph_table.h:1
(GraphTable: typed nodes/edges, neighbor sampling, node features) and
service/graph_brpc_server.cc (the brpc service exposing it to trainers
for GNN pipelines).

TPU-native redesign: graph sampling is HOST work feeding device batches
— the table lives in host RAM as CSR adjacency per edge type (numpy,
vectorized sampling) and serves either in-process (the usual pod
layout: every worker's host holds a shard) or over the same
length-prefixed TCP framing the C++ parameter server uses
(`GraphService`/`GraphClient`, python — the hot path of a GNN step is
the sampler, which is numpy-vectorized; the dense/sparse parameter
traffic stays on the C++ server).

Trust model / wire safety: the TCP protocol is the same typed
struct+numpy framing family as ps/service.py — an op name plus
primitively-typed fields (ints/floats/bools/strings) and dtyped numpy
buffers. NO pickle and no other code-bearing encoding crosses the
socket in either direction, the server dispatches only the explicit
method allowlist below (never getattr on attacker-chosen names), and
ndarray decoding is restricted to a numeric-dtype allowlist, so a
malicious peer can at worst feed wrong graph data. The protocol still
has no authentication or encryption: bind to loopback (the default) or
deploy on a trusted pod network, exactly like the C++ parameter server.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["GraphTable", "GraphService", "GraphClient"]


class GraphTable:
    """Typed graph in host memory (reference: common_graph_table.h).

    Edges are grouped by ``edge_type``; ``build()`` freezes them into CSR
    for vectorized neighbor sampling. Node features are named dense
    arrays keyed by node id.
    """

    def __init__(self, seed: int = 0):
        self._pending: Dict[str, List] = {}
        self._csr: Dict[str, Tuple[np.ndarray, np.ndarray, np.ndarray]] = {}
        self._nodes: Dict[str, np.ndarray] = {}      # node_type -> ids
        self._feats: Dict[str, Dict[str, np.ndarray]] = {}  # name->{id->row}
        self._feat_store: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        self._rng = np.random.default_rng(seed)

    # -- construction ------------------------------------------------------
    def add_graph_node(self, node_type: str, ids) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        prev = self._nodes.get(node_type)
        self._nodes[node_type] = ids if prev is None else \
            np.unique(np.concatenate([prev, ids]))

    def add_edges(self, edge_type: str, src, dst) -> None:
        src = np.asarray(src, np.int64).reshape(-1)
        dst = np.asarray(dst, np.int64).reshape(-1)
        if len(src) != len(dst):
            raise ValueError("src/dst length mismatch")
        if edge_type in self._csr and edge_type not in self._pending:
            # CSR exists with no pending source chunks — this edge type
            # came from load(), which clears _pending. Decompose the CSR
            # back into a pending chunk BEFORE invalidating, or the next
            # build() would rebuild from the new edges alone and silently
            # drop everything previously loaded.
            uniq, indptr, csr_dst = self._csr[edge_type]
            csr_src = np.repeat(uniq, np.diff(indptr))
            self._pending[edge_type] = [(csr_src, csr_dst)]
        self._pending.setdefault(edge_type, []).append((src, dst))
        self._csr.pop(edge_type, None)       # invalidate built form

    def set_node_feat(self, feat_name: str, ids, rows) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        rows = np.asarray(rows, np.float32)
        rows = rows.reshape(len(ids), -1)
        old_ids, old_rows = self._feat_store.get(
            feat_name, (np.empty(0, np.int64),
                        np.empty((0, rows.shape[1]), np.float32)))
        keep = ~np.isin(old_ids, ids)
        merged_ids = np.concatenate([old_ids[keep], ids])
        merged_rows = np.concatenate([old_rows[keep], rows])
        order = np.argsort(merged_ids)      # get_node_feat searchsorts
        self._feat_store[feat_name] = (merged_ids[order],
                                       merged_rows[order])

    def build(self) -> None:
        """Freeze pending edges into CSR (reference: build_sampler)."""
        for et, chunks in self._pending.items():
            if et in self._csr:
                continue
            src = np.concatenate([s for s, _ in chunks])
            dst = np.concatenate([d for _, d in chunks])
            order = np.argsort(src, kind="stable")
            src, dst = src[order], dst[order]
            uniq, starts = np.unique(src, return_index=True)
            indptr = np.append(starts, len(src))
            self._csr[et] = (uniq, indptr, dst)

    # -- queries -----------------------------------------------------------
    def _adj(self, edge_type: str):
        if edge_type not in self._csr:
            self.build()
        if edge_type not in self._csr:
            raise KeyError(f"no edges of type {edge_type!r}")
        return self._csr[edge_type]

    def sample_neighbors(self, edge_type: str, ids, sample_size: int,
                         replace: bool = False
                         ) -> Tuple[np.ndarray, np.ndarray]:
        """Up to ``sample_size`` neighbors per id (reference:
        graph_brpc_server sample_neighbors). Returns (flat_neighbors,
        counts) — counts[i] neighbors for ids[i], concatenated."""
        uniq, indptr, dst = self._adj(edge_type)
        ids = np.asarray(ids, np.int64).reshape(-1)
        pos = np.searchsorted(uniq, ids)
        found = (pos < len(uniq)) & (uniq[np.minimum(pos, len(uniq) - 1)]
                                     == ids)
        out: List[np.ndarray] = []
        counts = np.zeros(len(ids), np.int64)
        for i, (p, ok) in enumerate(zip(pos, found)):
            if not ok:
                continue
            nbrs = dst[indptr[p]:indptr[p + 1]]
            if len(nbrs) > sample_size and not replace:
                nbrs = self._rng.choice(nbrs, sample_size, replace=False)
            elif replace:
                nbrs = self._rng.choice(nbrs, sample_size, replace=True)
            counts[i] = len(nbrs)
            out.append(nbrs)
        flat = np.concatenate(out) if out else np.empty(0, np.int64)
        return flat, counts

    def random_sample_nodes(self, node_type: str,
                            sample_size: int) -> np.ndarray:
        ids = self._nodes.get(node_type)
        if ids is None or not len(ids):
            return np.empty(0, np.int64)
        k = min(sample_size, len(ids))
        return self._rng.choice(ids, k, replace=False)

    def get_node_feat(self, feat_name: str, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        fid, rows = self._feat_store.get(
            feat_name, (np.empty(0, np.int64),
                        np.empty((0, 0), np.float32)))
        dim = rows.shape[1] if rows.size else 0
        out = np.zeros((len(ids), dim), np.float32)
        pos = np.searchsorted(fid, ids)
        ok = (pos < len(fid)) & (fid[np.minimum(pos, len(fid) - 1)] == ids)
        out[ok] = rows[pos[ok]]
        return out

    def degree(self, edge_type: str, ids) -> np.ndarray:
        uniq, indptr, _ = self._adj(edge_type)
        ids = np.asarray(ids, np.int64).reshape(-1)
        pos = np.searchsorted(uniq, ids)
        ok = (pos < len(uniq)) & (uniq[np.minimum(pos, len(uniq) - 1)]
                                  == ids)
        deg = np.zeros(len(ids), np.int64)
        deg[ok] = indptr[pos[ok] + 1] - indptr[pos[ok]]
        return deg

    # -- checkpoint --------------------------------------------------------
    def save(self, dirname: str) -> None:
        os.makedirs(dirname, exist_ok=True)
        self.build()
        state = {"csr": self._csr, "nodes": self._nodes,
                 "feats": self._feat_store}
        with open(os.path.join(dirname, "graph_table.pkl"), "wb") as f:
            pickle.dump(state, f)

    def load(self, dirname: str) -> None:
        with open(os.path.join(dirname, "graph_table.pkl"), "rb") as f:
            state = pickle.load(f)
        self._csr = state["csr"]
        self._nodes = state["nodes"]
        self._feat_store = state["feats"]
        self._pending = {}


# ---------------------------------------------------------------------------
# TCP service (reference: graph_brpc_server.cc) — same length-prefixed
# framing family as the C++ parameter server. Messages are typed fields
# (see the module docstring's trust model): no pickle on the wire.
# ---------------------------------------------------------------------------

# field type tags
_T_NONE, _T_BOOL, _T_INT, _T_FLOAT, _T_STR, _T_NDARRAY, _T_LIST = range(7)

# only plain numeric buffers may decode into arrays: object/void/structured
# dtypes never cross the wire
_DTYPE_ALLOW = frozenset("biufc")


def _pack_value(v) -> bytes:
    if v is None:
        return struct.pack("<B", _T_NONE)
    if isinstance(v, (bool, np.bool_)):
        return struct.pack("<BB", _T_BOOL, int(v))
    if isinstance(v, (int, np.integer)):
        return struct.pack("<Bq", _T_INT, int(v))
    if isinstance(v, (float, np.floating)):
        return struct.pack("<Bd", _T_FLOAT, float(v))
    if isinstance(v, str):
        raw = v.encode("utf-8")
        return struct.pack("<BI", _T_STR, len(raw)) + raw
    if isinstance(v, (list, tuple)):
        parts = [struct.pack("<BI", _T_LIST, len(v))]
        parts += [_pack_value(x) for x in v]
        return b"".join(parts)
    arr = np.ascontiguousarray(v)
    if arr.dtype.kind not in _DTYPE_ALLOW:
        raise TypeError(f"graph wire protocol cannot carry dtype {arr.dtype}")
    dt = arr.dtype.str.encode("ascii")
    hdr = struct.pack("<BBB", _T_NDARRAY, len(dt), arr.ndim)
    shape = struct.pack(f"<{arr.ndim}q", *arr.shape)
    return hdr + dt + shape + arr.tobytes()


def _unpack_value(buf: memoryview, off: int):
    tag = buf[off]
    off += 1
    if tag == _T_NONE:
        return None, off
    if tag == _T_BOOL:
        return bool(buf[off]), off + 1
    if tag == _T_INT:
        return struct.unpack_from("<q", buf, off)[0], off + 8
    if tag == _T_FLOAT:
        return struct.unpack_from("<d", buf, off)[0], off + 8
    if tag == _T_STR:
        n = struct.unpack_from("<I", buf, off)[0]
        off += 4
        return bytes(buf[off:off + n]).decode("utf-8"), off + n
    if tag == _T_LIST:
        n = struct.unpack_from("<I", buf, off)[0]
        off += 4
        out = []
        for _ in range(n):
            v, off = _unpack_value(buf, off)
            out.append(v)
        return out, off
    if tag == _T_NDARRAY:
        dt_len, ndim = buf[off], buf[off + 1]
        off += 2
        dt = np.dtype(bytes(buf[off:off + dt_len]).decode("ascii"))
        off += dt_len
        if dt.kind not in _DTYPE_ALLOW:
            raise TypeError(f"refusing wire dtype {dt}")
        shape = struct.unpack_from(f"<{ndim}q", buf, off)
        off += 8 * ndim
        count = int(np.prod(shape)) if shape else 1
        nbytes = count * dt.itemsize
        arr = np.frombuffer(buf[off:off + nbytes], dtype=dt).reshape(shape)
        return arr.copy(), off + nbytes
    raise ValueError(f"unknown wire tag {tag}")


def _pack_fields(fields: Dict[str, object]) -> bytes:
    parts = [struct.pack("<I", len(fields))]
    for k, v in fields.items():
        raw = k.encode("utf-8")
        parts.append(struct.pack("<I", len(raw)))
        parts.append(raw)
        parts.append(_pack_value(v))
    return b"".join(parts)


def _unpack_fields(payload: bytes) -> Dict[str, object]:
    buf = memoryview(payload)
    n = struct.unpack_from("<I", buf, 0)[0]
    off = 4
    out: Dict[str, object] = {}
    for _ in range(n):
        klen = struct.unpack_from("<I", buf, off)[0]
        off += 4
        k = bytes(buf[off:off + klen]).decode("utf-8")
        off += klen
        v, off = _unpack_value(buf, off)
        out[k] = v
    return out


def _send_msg(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_msg(sock: socket.socket) -> bytes:
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("graph service closed")
        hdr += chunk
    n = struct.unpack("<Q", hdr)[0]
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("graph service closed")
        buf += chunk
    return bytes(buf)


# remote-callable surface: dispatch NEVER getattrs an attacker-chosen
# name, and host-side file I/O (save/load) is deliberately NOT remote
_SERVICE_OPS = frozenset({
    "add_graph_node", "add_edges", "set_node_feat", "build",
    "sample_neighbors", "random_sample_nodes", "get_node_feat", "degree",
})


class GraphService:
    """Serve a GraphTable over TCP (threaded; sampling is numpy work that
    releases the GIL in the hot loops). Wire format: typed struct+numpy
    fields — see the module docstring's trust model."""

    def __init__(self, table: GraphTable, host: str = "127.0.0.1",
                 port: int = 0):
        self.table = table
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.endpoint = "%s:%d" % self._srv.getsockname()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        while not self._stop.is_set():
            try:
                self._srv.settimeout(0.2)
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._client_loop, args=(conn,),
                             daemon=True).start()

    def _client_loop(self, conn):
        try:
            while True:
                req = _unpack_fields(_recv_msg(conn))
                op = req.pop("op", None)
                if not isinstance(op, str):
                    _send_msg(conn, _pack_fields(
                        {"ok": False, "error": "request missing 'op'"}))
                    continue
                if op == "stop":
                    _send_msg(conn, _pack_fields({"ok": True}))
                    return
                try:
                    if op not in _SERVICE_OPS:
                        raise ValueError(f"unknown graph op {op!r}")
                    out = getattr(self.table, op)(**req)
                    if isinstance(out, tuple):
                        out = list(out)
                    _send_msg(conn, _pack_fields({"ok": True,
                                                  "result": out}))
                except Exception as e:            # report, keep serving
                    _send_msg(conn, _pack_fields({"ok": False,
                                                  "error": repr(e)}))
        except (ConnectionError, EOFError, ValueError, KeyError,
                IndexError, TypeError, struct.error):
            # disconnected peer or an unparseable frame (truncated payload,
            # bad tag): close THIS connection quietly; the server and other
            # connections keep serving
            pass
        finally:
            conn.close()

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass


class GraphClient:
    """Remote GraphTable with the SAME method surface (reference:
    GraphBrpcClient)."""

    def __init__(self, endpoint: str):
        host, port = endpoint.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)), timeout=30)
        self._lock = threading.Lock()

    def _call(self, op: str, **kw):
        with self._lock:
            _send_msg(self._sock, _pack_fields({"op": op, **kw}))
            resp = _unpack_fields(_recv_msg(self._sock))
        if not resp.get("ok"):
            raise RuntimeError(f"graph service error: {resp.get('error')}")
        out = resp.get("result")
        # multi-array results (sample_neighbors) travel as a list
        if isinstance(out, list) and out and all(
                isinstance(x, np.ndarray) for x in out):
            return tuple(out)
        return out

    def add_graph_node(self, node_type, ids):
        return self._call("add_graph_node", node_type=node_type, ids=ids)

    def add_edges(self, edge_type, src, dst):
        return self._call("add_edges", edge_type=edge_type, src=src,
                          dst=dst)

    def set_node_feat(self, feat_name, ids, rows):
        return self._call("set_node_feat", feat_name=feat_name, ids=ids,
                          rows=rows)

    def build(self):
        return self._call("build")

    def sample_neighbors(self, edge_type, ids, sample_size,
                         replace=False):
        return self._call("sample_neighbors", edge_type=edge_type,
                          ids=ids, sample_size=sample_size,
                          replace=replace)

    def random_sample_nodes(self, node_type, sample_size):
        return self._call("random_sample_nodes", node_type=node_type,
                          sample_size=sample_size)

    def get_node_feat(self, feat_name, ids):
        return self._call("get_node_feat", feat_name=feat_name, ids=ids)

    def degree(self, edge_type, ids):
        return self._call("degree", edge_type=edge_type, ids=ids)

    def close(self):
        try:
            self._call("stop")
        except Exception:
            pass
        self._sock.close()
