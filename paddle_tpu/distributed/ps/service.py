"""Parameter-server process model: C++ server binary + python client.

reference parity: the brpc PS stack —
PSServer/PSClient (reference: paddle/fluid/distributed/service/
brpc_ps_server.h, brpc_ps_client.h), the async Communicator
(service/communicator.cc: grad queues merged and flushed by a background
thread), table sharding across servers, and the fleet PS role protocol
(python/paddle/distributed/fleet/base/role_maker.py: TRAINING_ROLE /
PADDLE_PSERVERS_IP_PORT_LIST env contract).

TPU-native redesign: the server is a standalone C++ process
(`_native/ps_server.cpp`, compiled on first use with g++) speaking a lean
length-prefixed TCP protocol; rows move as raw f32 buffers straight into
numpy, which jitted steps consume as ordinary host inputs. Keys are
sharded CLIENT-side across servers with the same splitmix64 hash the
server uses for lock striping, so adding servers rebalances without any
coordinator. The async communicator merges duplicate-key gradients
host-side before sending — the reference's merge_sparse_grad semantics.
"""

from __future__ import annotations

import hashlib
import os
import queue
import socket
import struct
import subprocess
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "_native", "ps_server.cpp")

# protocol op codes (keep in sync with ps_server.cpp)
_PING, _CREATE, _PULL_DENSE, _PUSH_DENSE, _PUSH_DENSE_GRAD = 0, 1, 2, 3, 4
_PULL_SPARSE, _PUSH_SPARSE_GRAD, _PUSH_SPARSE = 5, 6, 7
_SAVE, _LOAD, _STATS, _STOP, _KIND, _ADD_SPARSE = 8, 9, 10, 11, 12, 13

_OPT_KINDS = {"sgd": 0, "adagrad": 1, "adam": 2}


def _binary_path() -> Optional[str]:
    """Compile the server binary on first use, named by source hash."""
    try:
        with open(_SRC, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:12]
    except OSError:
        return None
    out = os.path.join(os.path.dirname(_SRC), f"ps_server-{digest}")
    if os.path.exists(out):
        return out
    tmp = out + f".tmp{os.getpid()}"
    try:
        subprocess.run(["g++", "-O2", "-std=c++17", "-pthread", _SRC,
                        "-o", tmp], check=True, capture_output=True)
        os.replace(tmp, out)
        return out
    except (subprocess.CalledProcessError, OSError):
        return None


def native_available() -> bool:
    return _binary_path() is not None


def _mix64(x):
    """splitmix64 over uint64 numpy arrays (wrapping arithmetic) — must
    match ps_server.cpp mix64 for deterministic placement. Vectorized:
    the owner computation sits on the hot pull/push path of every step."""
    x = np.asarray(x, np.uint64)
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


class PSServerHandle:
    """A running parameter-server process on this host.

    `host` is the BIND address: the loopback default keeps single-host
    tests private; multi-host fleets pass "0.0.0.0" (run_server does)
    so trainers reach the server over the pod's DCN."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        binary = _binary_path()
        if binary is None:
            raise RuntimeError(
                "no C++ toolchain: cannot build the PS server binary "
                "(paddle_tpu.distributed.ps.SparseTable is the in-process "
                "fallback)")
        self._proc = subprocess.Popen([binary, str(port), host],
                                      stdout=subprocess.PIPE, text=True)
        line = self._proc.stdout.readline()
        if not line.startswith("PS_SERVER_READY"):
            raise RuntimeError(f"ps_server failed to start: {line!r}")
        self.port = int(line.split()[1])
        client_host = "127.0.0.1" if host == "0.0.0.0" else host
        self.endpoint = f"{client_host}:{self.port}"

    def wait(self, timeout: Optional[float] = None) -> int:
        return self._proc.wait(timeout=timeout)

    def kill(self) -> None:
        if self._proc.poll() is None:
            self._proc.kill()
            self._proc.wait()


class _Conn:
    def __init__(self, endpoint: str):
        host, port = endpoint.rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port)))
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.lock = threading.Lock()

    def request(self, op: int, table: int, payload: bytes = b"") -> bytes:
        with self.lock:
            self.sock.sendall(struct.pack("<BIQ", op, table, len(payload))
                              + payload)
            hdr = self._recv(9)
            status, n = struct.unpack("<BQ", hdr)
            body = self._recv(n) if n else b""
        if status != 0:
            raise RuntimeError(f"ps server error: {body.decode()!r}")
        return body

    def _recv(self, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("ps server closed connection")
            buf += chunk
        return bytes(buf)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class PSClient:
    """Client over one or more PS endpoints with client-side key sharding.

    Dense table `t` lives wholly on server `t % nservers`; sparse rows
    are scattered `mix64(key) % nservers` (splitmix64 avoids hot servers
    for clustered id ranges, e.g. frequency-sorted vocabularies).
    """

    def __init__(self, endpoints: Sequence[str]):
        if not endpoints:
            raise ValueError("need at least one PS endpoint")
        self._conns = [_Conn(ep) for ep in endpoints]
        self.n = len(self._conns)
        self._kinds: Dict[int, str] = {}

    # -- admin ----------------------------------------------------------
    def ping(self) -> None:
        for c in self._conns:
            c.request(_PING, 0)

    def create_table(self, table_id: int, *, kind: str, dim: int,
                     rows: int = 0, optimizer: str = "adagrad",
                     lr: float = 0.05, seed: int = 0,
                     init_scale: float = 0.01) -> None:
        payload = struct.pack("<BBfQQIf", 0 if kind == "dense" else 1,
                              _OPT_KINDS[optimizer], lr, dim, rows, seed,
                              init_scale)
        self._kinds[table_id] = kind
        if kind == "dense":
            self._conns[table_id % self.n].request(_CREATE, table_id,
                                                   payload)
        else:
            for c in self._conns:       # sparse: every server holds a shard
                c.request(_CREATE, table_id, payload)

    def stop_servers(self) -> None:
        for c in self._conns:
            try:
                c.request(_STOP, 0)
            except (ConnectionError, RuntimeError, OSError):
                pass
            c.close()

    # -- dense ----------------------------------------------------------
    def pull_dense(self, table_id: int, rows: int, dim: int) -> np.ndarray:
        body = self._conns[table_id % self.n].request(_PULL_DENSE, table_id)
        return np.frombuffer(body, np.float32).reshape(rows, dim).copy()

    def push_dense(self, table_id: int, values: np.ndarray,
                   grad: bool = False) -> None:
        op = _PUSH_DENSE_GRAD if grad else _PUSH_DENSE
        self._conns[table_id % self.n].request(
            op, table_id, np.ascontiguousarray(values, np.float32).tobytes())

    # -- sparse ---------------------------------------------------------
    def _split(self, keys: np.ndarray) -> List[np.ndarray]:
        if self.n == 1:
            return [np.arange(len(keys))]
        owner = _mix64(keys) % np.uint64(self.n)
        return [np.nonzero(owner == s)[0] for s in range(self.n)]

    def pull_sparse(self, table_id: int, keys: np.ndarray,
                    dim: int) -> np.ndarray:
        keys = np.ascontiguousarray(keys, np.uint64)
        out = np.empty((len(keys), dim), np.float32)
        for s, idx in enumerate(self._split(keys)):
            if len(idx) == 0:
                continue
            sub = keys[idx]
            payload = struct.pack("<Q", len(sub)) + sub.tobytes()
            body = self._conns[s].request(_PULL_SPARSE, table_id, payload)
            out[idx] = np.frombuffer(body, np.float32).reshape(len(sub), dim)
        return out

    def push_sparse(self, table_id: int, keys: np.ndarray,
                    values: np.ndarray, grad: bool = True) -> None:
        keys = np.ascontiguousarray(keys, np.uint64)
        values = np.ascontiguousarray(values, np.float32)
        if grad and len(keys) > 1:
            # merge duplicate keys BEFORE the optimizer apply (dense
            # embedding-gradient semantics; the server applies each
            # request row sequentially, which differs for adagrad/adam)
            uniq, inv = np.unique(keys, return_inverse=True)
            if len(uniq) != len(keys):
                acc = np.zeros((len(uniq), values.shape[1]), np.float32)
                np.add.at(acc, inv, values)
                keys, values = uniq, acc
        self._send_rows(_PUSH_SPARSE_GRAD if grad else _PUSH_SPARSE,
                        table_id, keys, values)

    def add_sparse(self, table_id: int, keys: np.ndarray,
                   deltas: np.ndarray) -> None:
        """w[key] += delta — geo-SGD aggregation (reference: geo tables,
        communicator.cc GeoCommunicator send path). No client-side dedup:
        the server's += already sums duplicate keys."""
        self._send_rows(_ADD_SPARSE, table_id,
                        np.ascontiguousarray(keys, np.uint64),
                        np.ascontiguousarray(deltas, np.float32))

    def _send_rows(self, op: int, table_id: int, keys: np.ndarray,
                   values: np.ndarray) -> None:
        for s, idx in enumerate(self._split(keys)):
            if len(idx) == 0:
                continue
            sub, vals = keys[idx], values[idx]
            payload = struct.pack("<Q", len(sub)) + sub.tobytes() \
                + vals.tobytes()
            self._conns[s].request(op, table_id, payload)

    # -- checkpoint / stats ---------------------------------------------
    def table_kind(self, table_id: int) -> str:
        """'dense' | 'sparse' | 'absent' — queried from the servers when
        this client did not create the table itself (e.g. a separate
        checkpointing process)."""
        kind = self._kinds.get(table_id)
        if kind is None:
            owner = self._conns[table_id % self.n]
            k = owner.request(_KIND, table_id)[0]
            kind = {0: "dense", 1: "sparse", 2: "absent"}[k]
            if kind != "absent":
                self._kinds[table_id] = kind
        return kind

    def _table_conns(self, table_id: int):
        """(shard, conn) pairs owning this table: the single owner for a
        dense table, every server for a sparse one."""
        if self.table_kind(table_id) == "dense":
            s = table_id % self.n
            return [(s, self._conns[s])]
        return list(enumerate(self._conns))

    def save(self, table_id: int, dirname: str) -> None:
        os.makedirs(dirname, exist_ok=True)
        for s, c in self._table_conns(table_id):
            path = os.path.join(dirname, f"table{table_id}.shard{s}")
            c.request(_SAVE, table_id, path.encode())

    def load(self, table_id: int, dirname: str) -> None:
        for s, c in self._table_conns(table_id):
            path = os.path.join(dirname, f"table{table_id}.shard{s}")
            if os.path.exists(path):
                c.request(_LOAD, table_id, path.encode())

    def num_rows(self, table_id: int) -> int:
        return sum(struct.unpack("<Q", c.request(_STATS, table_id))[0]
                   for c in self._conns)

    def close(self) -> None:
        for c in self._conns:
            c.close()


class AsyncCommunicator:
    """Background gradient sender (reference: service/communicator.cc).

    Worker threads enqueue sparse gradients; one sender thread merges
    duplicate keys (gradient sum — the reference's merge_sparse_grad) and
    pushes batches, overlapping PS traffic with the next device step.
    `send_every` bounds staleness; `flush()` drains synchronously.
    """

    def __init__(self, client: PSClient, send_every: float = 0.01):
        self._client = client
        self._q: "queue.Queue" = queue.Queue()
        self._send_every = send_every
        self._err: Optional[BaseException] = None
        self._stop = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def push_sparse_grad(self, table_id: int, keys: np.ndarray,
                         grads: np.ndarray) -> None:
        if self._err is not None:
            raise RuntimeError("communicator failed") from self._err
        self._idle.clear()
        self._q.put((table_id, np.asarray(keys), np.asarray(grads)))

    def _drain_batch(self) -> Dict[int, Tuple[np.ndarray, np.ndarray]]:
        """Concatenate everything queued per table; the duplicate-key SUM
        happens vectorized inside PSClient.push_sparse."""
        pending: Dict[int, list] = {}
        while True:
            try:
                table, keys, grads = self._q.get_nowait()
            except queue.Empty:
                break
            pending.setdefault(table, []).append((keys, grads))
        return {t: (np.concatenate([k for k, _ in items]),
                    np.concatenate([g for _, g in items]))
                for t, items in pending.items()}

    def _run(self) -> None:
        try:
            while not self._stop.is_set() or not self._q.empty():
                merged = self._drain_batch()
                if not merged:
                    self._idle.set()
                    time.sleep(self._send_every)
                    continue
                for table, (keys, grads) in merged.items():
                    self._client.push_sparse(
                        table, keys.astype(np.uint64),
                        grads.astype(np.float32), grad=True)
                if self._q.empty():
                    self._idle.set()
        except BaseException as e:          # surfaced on next push/flush
            self._err = e
            self._idle.set()

    def flush(self, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        while not (self._q.empty() and self._idle.is_set()):
            if self._err is not None:
                raise RuntimeError("communicator failed") from self._err
            if time.monotonic() > deadline:
                raise TimeoutError("communicator flush timed out")
            time.sleep(0.002)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=30.0)
        if self._err is not None:
            raise RuntimeError("communicator failed") from self._err


class GeoCommunicator:
    """Geo-SGD training mode (reference: communicator.cc GeoCommunicator,
    distributed/table geo tables): each worker trains a LOCAL copy of the
    touched rows and periodically pushes parameter DELTAS, which servers
    sum — communication-efficient async training for sparse models.

    Usage: `pull(keys)` serves rows from a local trainable cache,
    `update(keys, rows)` writes trained rows back, and `maybe_sync()`
    (call once per step) pushes `local - base` deltas and refreshes the
    base every `trigger_steps`.
    """

    def __init__(self, client: PSClient, table_id: int, dim: int,
                 trigger_steps: int = 10):
        self._client = client
        self._table = table_id
        self._dim = dim
        self._trigger = trigger_steps
        self._step = 0
        self._local: Dict[int, np.ndarray] = {}   # key -> current row
        self._base: Dict[int, np.ndarray] = {}    # key -> row at last sync
        self._dirty: set = set()                  # keys updated since sync

    def pull(self, keys: np.ndarray) -> np.ndarray:
        """Rows for `keys`, served from the local cache when present."""
        keys = np.asarray(keys, np.uint64).reshape(-1)
        if len(keys) == 0:
            return np.zeros((0, self._dim), np.float32)
        missing = [int(k) for k in keys if int(k) not in self._local]
        if missing:
            fetched = self._client.pull_sparse(
                self._table, np.asarray(missing, np.uint64), self._dim)
            for k, row in zip(missing, fetched):
                self._local[k] = row.copy()
                self._base[k] = row.copy()
        return np.stack([self._local[int(k)] for k in keys])

    def update(self, keys: np.ndarray, rows: np.ndarray) -> None:
        """Write locally-trained rows back into the cache."""
        keys = np.asarray(keys, np.uint64).reshape(-1)
        for k, row in zip(keys, np.asarray(rows, np.float32)):
            self._local[int(k)] = row.copy()
            self._dirty.add(int(k))

    def maybe_sync(self) -> bool:
        """Every trigger_steps: push accumulated deltas, refresh bases
        from the server (absorbing other workers' deltas)."""
        self._step += 1
        if self._step % self._trigger:
            return False
        if self._dirty:
            # only the keys touched since the last sync travel (the
            # reference GeoCommunicator keeps the same delta-id sets);
            # untouched cache entries are dropped so the local cache does
            # not grow with the worker's lifetime key set
            keys = np.fromiter(self._dirty, np.uint64, len(self._dirty))
            deltas = np.stack([self._local[int(k)] - self._base[int(k)]
                               for k in keys])
            self._client.add_sparse(self._table, keys, deltas)
            fresh = self._client.pull_sparse(self._table, keys, self._dim)
            clean = set(self._local) - self._dirty
            for k, row in zip(keys, fresh):
                self._local[int(k)] = row.copy()
                self._base[int(k)] = row.copy()
            for k in clean:
                self._local.pop(k, None)
                self._base.pop(k, None)
            self._dirty.clear()
        return True


# ---------------------------------------------------------------------------
# fleet PS-mode role protocol (reference: fleet/base/role_maker.py env vars)
# ---------------------------------------------------------------------------


def role_from_env() -> str:
    """'TRAINER' | 'PSERVER' from TRAINING_ROLE (reference contract)."""
    return os.environ.get("TRAINING_ROLE", "TRAINER").upper()


def server_endpoints_from_env() -> List[str]:
    eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
    return [e for e in eps.split(",") if e]


def run_server(port: Optional[int] = None) -> PSServerHandle:
    """Start this host's PS process (reference: fleet.run_server).
    Binds all interfaces so trainers on other hosts can connect."""
    if port is None:
        ep = os.environ.get("PADDLE_PORT")
        port = int(ep) if ep else 0
    return PSServerHandle(port=port, host="0.0.0.0")
