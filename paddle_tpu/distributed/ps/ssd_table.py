"""SSD (disk-backed) sparse table: the PS industrial tail.

reference parity: paddle/fluid/distributed/table/ssd_sparse_table.h:21 —
a sparse table whose cold rows live on local SSD (rocksdb in the
reference) behind an in-memory hot cache, so embedding tables larger
than host RAM still serve pull/push at memory speed for the hot set.

TPU-native redesign: a log-structured append-only file + an in-memory
offset index replaces rocksdb (no external deps): the newest version of
a row is wherever it was last appended; eviction appends the row and
drops it from the hot cache; `compact()` rewrites only live offsets.
Rows are materialized LAZILY on first touch with a per-row deterministic
initializer (hash-seeded), so a 10^9-row table costs nothing until ids
arrive — the reference's SSD table is lazy the same way.

Protocol-compatible with :class:`SparseTable` (pull/push/state_dict), so
`DistributedEmbedding(table=SSDSparseTable(...))` works unchanged.
"""

from __future__ import annotations

import os
import struct
from collections import OrderedDict
from typing import Dict, Optional

import numpy as np

__all__ = ["SSDSparseTable"]

_HDR = struct.Struct("<qf")          # row_id:int64, g2:float32


class SSDSparseTable:
    """Disk-backed sparse embedding shard with an LRU hot cache.

    ``cache_rows`` caps host-memory residency; everything beyond it
    spills to ``path`` (a log-structured file). The pull/push/optimizer
    semantics match :class:`SparseTable` (adagrad | sgd, duplicate-id
    gradient accumulation before the update)."""

    def __init__(self, num_rows: int, dim: int, cache_rows: int = 100_000,
                 path: Optional[str] = None, optimizer: str = "adagrad",
                 lr: float = 0.05, shard_id: int = 0, num_shards: int = 1,
                 seed: int = 0):
        if optimizer not in ("adagrad", "sgd"):
            raise ValueError(f"unknown PS optimizer {optimizer!r}")
        self.num_rows = num_rows
        self.dim = dim
        self.cache_rows = max(1, int(cache_rows))
        self.optimizer = optimizer
        self.lr = lr
        self.shard_id = shard_id
        self.num_shards = num_shards
        self.seed = seed
        self._rec = _HDR.size + 4 * dim
        if path is None:
            import tempfile
            fd, path = tempfile.mkstemp(prefix="ps_ssd_", suffix=".log")
            os.close(fd)
            self._own_path = True
        else:
            self._own_path = False
        self.path = path
        self._log = open(path, "a+b")
        self._log.seek(0, os.SEEK_END)
        # hot cache: row_id -> (vec[dim] f32, g2 float); LRU order
        self._cache: "OrderedDict[int, tuple]" = OrderedDict()
        self._index: Dict[int, int] = {}      # row_id -> log offset
        self.pull_count = 0
        self.push_count = 0
        self.evict_count = 0
        # per-source row reads: the host-cache/SSD split a tier manager
        # (paddle_tpu.recsys.tiering) surfaces as host vs ssd hit rates
        self.cache_hit_count = 0
        self.log_read_count = 0
        self.lazy_init_count = 0

    # -- row lifecycle -----------------------------------------------------
    def _init_row(self, rid: int) -> np.ndarray:
        """Deterministic lazy init: same row always initializes the same
        regardless of touch order / cache state (the eager SparseTable
        cannot promise that across shard counts; a disk table must)."""
        rng = np.random.default_rng((self.seed * 0x9E3779B1 + rid)
                                    & 0xFFFFFFFF)
        scale = 1.0 / np.sqrt(self.dim)
        return rng.uniform(-scale, scale, (self.dim,)).astype(np.float32)

    def _read_row(self, offset: int):
        self._log.seek(offset)
        buf = self._log.read(self._rec)
        rid, g2 = _HDR.unpack_from(buf)
        vec = np.frombuffer(buf, np.float32, self.dim, _HDR.size).copy()
        return rid, vec, g2

    def _append_row(self, rid: int, vec: np.ndarray, g2: float) -> int:
        self._log.seek(0, os.SEEK_END)
        offset = self._log.tell()
        self._log.write(_HDR.pack(rid, g2))
        self._log.write(np.ascontiguousarray(vec, np.float32).tobytes())
        return offset

    def _evict_to_cap(self):
        while len(self._cache) > self.cache_rows:
            rid, (vec, g2) = self._cache.popitem(last=False)   # LRU
            self._index[rid] = self._append_row(rid, vec, g2)
            self.evict_count += 1

    def _load(self, rid: int):
        """Row into the hot cache (disk read or lazy init); returns the
        cache entry and refreshes recency."""
        hit = self._cache.get(rid)
        if hit is not None:
            self.cache_hit_count += 1
            self._cache.move_to_end(rid)
            return hit
        off = self._index.get(rid)
        if off is not None:
            self.log_read_count += 1
            stored_rid, vec, g2 = self._read_row(off)
            assert stored_rid == rid, "corrupt SSD table index"
        else:
            self.lazy_init_count += 1
            vec, g2 = self._init_row(rid), 0.0
        self._cache[rid] = (vec, g2)
        self._evict_to_cap()
        return self._cache.get(rid) or (vec, g2)

    # -- SparseTable protocol ---------------------------------------------
    def _local(self, ids: np.ndarray) -> np.ndarray:
        if self.num_shards > 1:
            if not ((ids % self.num_shards) == self.shard_id).all():
                raise ValueError("ids routed to the wrong shard")
            return ids // self.num_shards
        return ids

    def pull(self, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        local = self._local(ids)
        self.pull_count += 1
        out = np.empty((len(local), self.dim), np.float32)
        for i, rid in enumerate(local):
            out[i] = self._load(int(rid))[0]
        return out

    def push(self, ids, grads) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        local = self._local(ids)
        uniq, inv = np.unique(local, return_inverse=True)
        acc = np.zeros((len(uniq), self.dim), np.float32)
        np.add.at(acc, inv, grads)
        for i, rid in enumerate(uniq):
            rid = int(rid)
            vec, g2 = self._load(rid)
            g = acc[i]
            if self.optimizer == "adagrad":
                g2 = g2 + float((g ** 2).mean())
                vec = vec - self.lr * g / (np.sqrt(g2) + 1e-10)
            else:
                vec = vec - self.lr * g
            self._cache[rid] = (vec.astype(np.float32), g2)
        self.push_count += 1

    # -- raw row access (tier promotion/demotion; no optimizer step) -------
    def read_rows(self, ids):
        """(vecs [n, dim], g2 [n]) without cache promotion or pull
        accounting — the tier manager's raw read (``_load_cold`` walk,
        so a promotion scan never thrashes the hot cache)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        local = self._local(ids)
        vecs = np.empty((len(local), self.dim), np.float32)
        g2 = np.empty((len(local),), np.float32)
        for i, rid in enumerate(local):
            v, g = self._load_cold(int(rid))
            vecs[i], g2[i] = v, g
        return vecs, g2

    def write_rows(self, ids, vecs, g2=None) -> None:
        """Overwrite rows (and adagrad state) verbatim — the tier
        manager's demotion write-back. NOT a push: no gradient math."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        local = self._local(ids)
        vecs = np.asarray(vecs, np.float32).reshape(len(local), self.dim)
        g2 = (np.zeros(len(local), np.float32) if g2 is None
              else np.asarray(g2, np.float32).reshape(-1))
        for i, rid in enumerate(local):
            self._cache[int(rid)] = (vecs[i].copy(), float(g2[i]))
        self._evict_to_cap()

    # -- maintenance -------------------------------------------------------
    @property
    def resident_rows(self) -> int:
        return len(self._cache)

    @property
    def spilled_rows(self) -> int:
        return len([r for r in self._index if r not in self._cache])

    def log_bytes(self) -> int:
        self._log.seek(0, os.SEEK_END)
        return self._log.tell()

    def compact(self):
        """Rewrite the log keeping only each row's LIVE version (the
        reference compaction is rocksdb's; a log-structured file needs an
        explicit pass)."""
        tmp_path = self.path + ".compact"
        with open(tmp_path, "wb") as tmp:
            new_index = {}
            for rid, off in self._index.items():
                if rid in self._cache:
                    continue                   # hot copy is newer
                _, vec, g2 = self._read_row(off)
                new_index[rid] = tmp.tell()
                tmp.write(_HDR.pack(rid, g2))
                tmp.write(vec.tobytes())
        self._log.close()
        os.replace(tmp_path, self.path)
        self._log = open(self.path, "a+b")
        self._index = new_index

    # -- checkpoint (SparseTable-compatible surface) -----------------------
    def state_dict(self) -> Dict[str, np.ndarray]:
        """All TOUCHED rows (hot + spilled) as dense arrays keyed by id —
        round-trips through load_state_dict; untouched rows re-derive
        from the deterministic initializer."""
        rows, vecs, g2s = [], [], []
        for rid in sorted(set(self._cache) | set(self._index)):
            vec, g2 = self._load_cold(rid)
            rows.append(rid)
            vecs.append(vec)
            g2s.append(g2)
        return {"row_ids": np.asarray(rows, np.int64),
                "data": (np.stack(vecs) if vecs
                         else np.zeros((0, self.dim), np.float32)),
                "g2": np.asarray(g2s, np.float32)}

    def _load_cold(self, rid: int):
        """Read a row WITHOUT promoting it into the cache (checkpoint
        walks must not thrash the hot set)."""
        hit = self._cache.get(rid)
        if hit is not None:
            return hit
        off = self._index.get(rid)
        if off is not None:
            _, vec, g2 = self._read_row(off)
            return vec, g2
        return self._init_row(rid), 0.0

    def load_state_dict(self, state):
        ids = np.asarray(state["row_ids"], np.int64)
        data = np.asarray(state["data"], np.float32)
        g2 = np.asarray(state.get("g2",
                                  np.zeros(len(ids), np.float32)),
                        np.float32)
        self._cache.clear()
        self._index.clear()
        self._log.truncate(0)
        for i, rid in enumerate(ids):
            self._cache[int(rid)] = (data[i].copy(), float(g2[i]))
            self._evict_to_cap()

    def save(self, dirname: str):
        os.makedirs(dirname, exist_ok=True)
        np.savez(os.path.join(dirname, f"ssd_shard_{self.shard_id}.npz"),
                 **self.state_dict())

    def load(self, dirname: str):
        with np.load(os.path.join(
                dirname, f"ssd_shard_{self.shard_id}.npz")) as z:
            self.load_state_dict({k: z[k] for k in z.files})

    def close(self):
        try:
            self._log.close()
        finally:
            if self._own_path and os.path.exists(self.path):
                os.unlink(self.path)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
