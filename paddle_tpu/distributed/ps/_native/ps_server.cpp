// Parameter-server core: a standalone TCP server process hosting dense and
// sparse (hash) parameter tables with row-wise optimizer appliers.
//
// reference parity: paddle/fluid/distributed/service/brpc_ps_server.h
// (PsService::service dispatch), distributed/table/common_dense_table.cc
// (dense pull/push + sgd/adam appliers), common_sparse_table.cc (shard
// hash tables, lazy row init, pull_sparse/push_sparse_grad),
// service/communicator.cc (the async client lives in python).
//
// TPU-native redesign notes: the accelerator never talks to this process —
// workers pull rows into host numpy buffers, feed them to jitted steps as
// ordinary inputs, and push gradients back. The server is therefore plain
// portable C++ (sockets + threads, no RDMA/brpc): on a TPU pod the hosts'
// commodity NICs and DCN carry this traffic, and the hot math (row apply)
// is a contiguous float loop the compiler vectorizes.
//
// Protocol (little-endian):
//   request  = [u8 op][u32 table_id][u64 nbytes][payload]
//   response = [u8 status][u64 nbytes][payload]    status: 0 ok, 1 error
// Ops: 0 ping, 1 create_table, 2 pull_dense, 3 push_dense(set),
//      4 push_dense_grad, 5 pull_sparse, 6 push_sparse_grad,
//      7 push_sparse(set), 8 save, 9 load, 10 stats, 11 stop.
//
// Build: g++ -O2 -std=c++17 -pthread ps_server.cpp -o ps_server

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <memory>
#include <mutex>
#include <string>
#include <chrono>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kNumShards = 16;  // lock striping for concurrent clients

enum Op : uint8_t {
  kPing = 0,
  kCreateTable = 1,
  kPullDense = 2,
  kPushDense = 3,
  kPushDenseGrad = 4,
  kPullSparse = 5,
  kPushSparseGrad = 6,
  kPushSparse = 7,
  kSave = 8,
  kLoad = 9,
  kStats = 10,
  kStop = 11,
  kKind = 12,
  kAddSparse = 13,   // w[key] += delta (geo-SGD aggregation)
};

enum OptKind : uint8_t { kSGD = 0, kAdagrad = 1, kAdam = 2 };

// splitmix64: deterministic per-(seed, key) row init, same rows no matter
// which server/shard ends up owning a key.
inline uint64_t mix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

inline float uniform_from(uint64_t h, float scale) {
  // top 24 bits -> [0, 1) -> [-scale, scale)
  float u = static_cast<float>(h >> 40) * (1.0f / 16777216.0f);
  return (2.0f * u - 1.0f) * scale;
}

struct OptConfig {
  OptKind kind = kSGD;
  float lr = 0.05f;
  // adam hyperparameters (fixed defaults, matching the reference ops)
  float beta1 = 0.9f, beta2 = 0.999f, eps = 1e-8f;
};

// state floats per weight for each optimizer
inline int slots_per_dim(OptKind k) {
  switch (k) {
    case kSGD: return 0;
    case kAdagrad: return 1;   // g2 accumulator
    case kAdam: return 2;      // m, v
  }
  return 0;
}

void apply_row(const OptConfig& opt, float* w, float* state, const float* g,
               uint64_t dim, uint64_t t) {
  switch (opt.kind) {
    case kSGD:
      for (uint64_t i = 0; i < dim; ++i) w[i] -= opt.lr * g[i];
      break;
    case kAdagrad:
      for (uint64_t i = 0; i < dim; ++i) {
        state[i] += g[i] * g[i];
        w[i] -= opt.lr * g[i] / (std::sqrt(state[i]) + 1e-6f);
      }
      break;
    case kAdam: {
      float* m = state;
      float* v = state + dim;
      float bc1 = 1.0f - std::pow(opt.beta1, static_cast<float>(t));
      float bc2 = 1.0f - std::pow(opt.beta2, static_cast<float>(t));
      for (uint64_t i = 0; i < dim; ++i) {
        m[i] = opt.beta1 * m[i] + (1.0f - opt.beta1) * g[i];
        v[i] = opt.beta2 * v[i] + (1.0f - opt.beta2) * g[i] * g[i];
        w[i] -= opt.lr * (m[i] / bc1) / (std::sqrt(v[i] / bc2) + opt.eps);
      }
      break;
    }
  }
}

struct DenseTable {
  uint64_t rows = 0, dim = 0;
  OptConfig opt;
  uint64_t step = 0;
  std::vector<float> w, state;
  std::mutex mu;
};

struct SparseShard {
  std::unordered_map<uint64_t, uint32_t> index;  // key -> row slot
  std::vector<float> w;      // slot * dim
  std::vector<float> state;  // slot * dim * slots_per_dim
  std::vector<uint64_t> keys;  // slot -> key (for save)
  std::mutex mu;
};

struct SparseTable {
  uint64_t dim = 0;
  OptConfig opt;
  uint32_t seed = 0;
  float init_scale = 0.01f;
  std::atomic<uint64_t> step{0};
  SparseShard shards[kNumShards];

  // returns pointer to the row, creating (deterministic init) if absent.
  // caller must hold the shard lock.
  float* row(SparseShard& sh, uint64_t key) {
    auto it = sh.index.find(key);
    uint32_t slot;
    if (it == sh.index.end()) {
      slot = static_cast<uint32_t>(sh.keys.size());
      sh.index.emplace(key, slot);
      sh.keys.push_back(key);
      sh.w.resize(sh.w.size() + dim);
      sh.state.resize(sh.state.size() + dim * slots_per_dim(opt.kind), 0.f);
      float* w = &sh.w[static_cast<size_t>(slot) * dim];
      for (uint64_t i = 0; i < dim; ++i)
        w[i] = uniform_from(mix64((uint64_t(seed) << 32) ^ mix64(key) ^ i),
                            init_scale);
      return w;
    }
    slot = it->second;
    return &sh.w[static_cast<size_t>(slot) * dim];
  }
  float* row_state(SparseShard& sh, uint64_t key) {
    int spd = slots_per_dim(opt.kind);
    if (spd == 0) return nullptr;
    return &sh.state[static_cast<size_t>(sh.index[key]) * dim * spd];
  }
  static int shard_of(uint64_t key) {
    return static_cast<int>(mix64(key) % kNumShards);
  }
};

struct Server {
  std::unordered_map<uint32_t, std::unique_ptr<DenseTable>> dense;
  std::unordered_map<uint32_t, std::unique_ptr<SparseTable>> sparse;
  std::mutex tables_mu;
  std::atomic<bool> stop{false};
  std::atomic<int> active_conns{0};
  int listen_fd = -1;

  DenseTable* dense_at(uint32_t id) {
    std::lock_guard<std::mutex> g(tables_mu);
    auto it = dense.find(id);
    return it == dense.end() ? nullptr : it->second.get();
  }
  SparseTable* sparse_at(uint32_t id) {
    std::lock_guard<std::mutex> g(tables_mu);
    auto it = sparse.find(id);
    return it == sparse.end() ? nullptr : it->second.get();
  }
};

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool respond(int fd, uint8_t status, const void* payload, uint64_t n) {
  char hdr[9];
  hdr[0] = static_cast<char>(status);
  std::memcpy(hdr + 1, &n, 8);
  if (!write_full(fd, hdr, 9)) return false;
  if (n && !write_full(fd, payload, n)) return false;
  return true;
}

bool respond_err(int fd, const std::string& msg) {
  return respond(fd, 1, msg.data(), msg.size());
}

template <typename T>
T rd(const char*& p) {
  T v;
  std::memcpy(&v, p, sizeof(T));
  p += sizeof(T);
  return v;
}

void handle_create(Server& srv, const std::vector<char>& body, uint32_t id,
                   int fd) {
  const char* p = body.data();
  uint8_t kind = rd<uint8_t>(p);
  OptConfig opt;
  opt.kind = static_cast<OptKind>(rd<uint8_t>(p));
  opt.lr = rd<float>(p);
  uint64_t dim = rd<uint64_t>(p);
  uint64_t rows = rd<uint64_t>(p);
  uint32_t seed = rd<uint32_t>(p);
  float init_scale = rd<float>(p);
  std::lock_guard<std::mutex> g(srv.tables_mu);
  if (kind == 0) {
    auto t = std::make_unique<DenseTable>();
    t->rows = rows;
    t->dim = dim;
    t->opt = opt;
    t->w.resize(rows * dim);
    for (uint64_t i = 0; i < rows * dim; ++i)
      t->w[i] = uniform_from(mix64((uint64_t(seed) << 32) ^ i), init_scale);
    t->state.resize(rows * dim * slots_per_dim(opt.kind), 0.f);
    srv.dense[id] = std::move(t);
  } else {
    auto t = std::make_unique<SparseTable>();
    t->dim = dim;
    t->opt = opt;
    t->seed = seed;
    t->init_scale = init_scale;
    srv.sparse[id] = std::move(t);
  }
  respond(fd, 0, nullptr, 0);
}

void handle_pull_sparse(SparseTable& t, const std::vector<char>& body,
                        int fd) {
  if (body.size() < 8) { respond_err(fd, "short request"); return; }
  const char* p = body.data();
  uint64_t n = rd<uint64_t>(p);
  // bound BEFORE multiplying: wire-controlled n must not overflow
  if (n > (body.size() - 8) / 8 || body.size() != 8 + n * 8) {
    respond_err(fd, "pull_sparse size mismatch");
    return;
  }
  const uint64_t* keys = reinterpret_cast<const uint64_t*>(p);
  std::vector<float> out(n * t.dim);
  for (uint64_t i = 0; i < n; ++i) {
    SparseShard& sh = t.shards[SparseTable::shard_of(keys[i])];
    std::lock_guard<std::mutex> g(sh.mu);
    const float* w = t.row(sh, keys[i]);
    std::memcpy(&out[i * t.dim], w, t.dim * sizeof(float));
  }
  respond(fd, 0, out.data(), out.size() * sizeof(float));
}

void handle_add_sparse(SparseTable& t, const std::vector<char>& body,
                       int fd) {
  // geo-SGD: workers train locally and push PARAMETER DELTAS which are
  // summed into the global table (reference: distributed/table geo mode,
  // communicator.cc GeoCommunicator).
  if (body.size() < 8) { respond_err(fd, "short request"); return; }
  const char* p = body.data();
  uint64_t n = rd<uint64_t>(p);
  if (n > (body.size() - 8) / 8 ||
      body.size() != 8 + n * 8 + n * t.dim * sizeof(float)) {
    respond_err(fd, "add_sparse size mismatch");
    return;
  }
  const uint64_t* keys = reinterpret_cast<const uint64_t*>(p);
  const float* vals =
      reinterpret_cast<const float*>(p + n * sizeof(uint64_t));
  for (uint64_t i = 0; i < n; ++i) {
    SparseShard& sh = t.shards[SparseTable::shard_of(keys[i])];
    std::lock_guard<std::mutex> g(sh.mu);
    float* w = t.row(sh, keys[i]);
    for (uint64_t d = 0; d < t.dim; ++d) w[d] += vals[i * t.dim + d];
  }
  respond(fd, 0, nullptr, 0);
}

void handle_push_sparse(SparseTable& t, const std::vector<char>& body,
                        bool is_grad, int fd) {
  if (body.size() < 8) { respond_err(fd, "short request"); return; }
  const char* p = body.data();
  uint64_t n = rd<uint64_t>(p);
  if (n > (body.size() - 8) / 8 ||
      body.size() != 8 + n * 8 + n * t.dim * sizeof(float)) {
    respond_err(fd, "push_sparse size mismatch");
    return;
  }
  const uint64_t* keys = reinterpret_cast<const uint64_t*>(p);
  const float* vals =
      reinterpret_cast<const float*>(p + n * sizeof(uint64_t));
  uint64_t step = is_grad ? t.step.fetch_add(1) + 1 : 0;
  for (uint64_t i = 0; i < n; ++i) {
    SparseShard& sh = t.shards[SparseTable::shard_of(keys[i])];
    std::lock_guard<std::mutex> g(sh.mu);
    float* w = t.row(sh, keys[i]);
    if (is_grad) {
      apply_row(t.opt, w, t.row_state(sh, keys[i]), &vals[i * t.dim], t.dim,
                step);
    } else {
      std::memcpy(w, &vals[i * t.dim], t.dim * sizeof(float));
    }
  }
  respond(fd, 0, nullptr, 0);
}

void handle_save(Server& srv, const std::vector<char>& body, uint32_t id,
                 int fd) {
  std::string path(body.begin(), body.end());
  std::ofstream f(path, std::ios::binary);
  if (!f) {
    respond_err(fd, "cannot open " + path);
    return;
  }
  if (DenseTable* t = srv.dense_at(id)) {
    std::lock_guard<std::mutex> g(t->mu);
    uint8_t kind = 0;
    f.write(reinterpret_cast<const char*>(&kind), 1);
    f.write(reinterpret_cast<const char*>(&t->rows), 8);
    f.write(reinterpret_cast<const char*>(&t->dim), 8);
    f.write(reinterpret_cast<const char*>(t->w.data()),
            t->w.size() * sizeof(float));
    f.write(reinterpret_cast<const char*>(t->state.data()),
            t->state.size() * sizeof(float));
  } else if (SparseTable* t = srv.sparse_at(id)) {
    uint8_t kind = 1;
    f.write(reinterpret_cast<const char*>(&kind), 1);
    f.write(reinterpret_cast<const char*>(&t->dim), 8);
    int spd = slots_per_dim(t->opt.kind);
    for (auto& sh : t->shards) {
      std::lock_guard<std::mutex> g(sh.mu);
      uint64_t n = sh.keys.size();
      f.write(reinterpret_cast<const char*>(&n), 8);
      f.write(reinterpret_cast<const char*>(sh.keys.data()), n * 8);
      f.write(reinterpret_cast<const char*>(sh.w.data()),
              n * t->dim * sizeof(float));
      f.write(reinterpret_cast<const char*>(sh.state.data()),
              n * t->dim * spd * sizeof(float));
    }
  } else {
    respond_err(fd, "no such table");
    return;
  }
  respond(fd, 0, nullptr, 0);
}

void handle_load(Server& srv, const std::vector<char>& body, uint32_t id,
                 int fd) {
  std::string path(body.begin(), body.end());
  std::ifstream f(path, std::ios::binary);
  if (!f) {
    respond_err(fd, "cannot open " + path);
    return;
  }
  uint8_t kind;
  f.read(reinterpret_cast<char*>(&kind), 1);
  if (kind == 0) {
    DenseTable* t = srv.dense_at(id);
    if (!t) {
      respond_err(fd, "dense table not created");
      return;
    }
    std::lock_guard<std::mutex> g(t->mu);
    f.read(reinterpret_cast<char*>(&t->rows), 8);
    f.read(reinterpret_cast<char*>(&t->dim), 8);
    t->w.resize(t->rows * t->dim);
    t->state.resize(t->rows * t->dim * slots_per_dim(t->opt.kind));
    f.read(reinterpret_cast<char*>(t->w.data()),
           t->w.size() * sizeof(float));
    f.read(reinterpret_cast<char*>(t->state.data()),
           t->state.size() * sizeof(float));
  } else {
    SparseTable* t = srv.sparse_at(id);
    if (!t) {
      respond_err(fd, "sparse table not created");
      return;
    }
    f.read(reinterpret_cast<char*>(&t->dim), 8);
    int spd = slots_per_dim(t->opt.kind);
    for (auto& sh : t->shards) {
      std::lock_guard<std::mutex> g(sh.mu);
      uint64_t n;
      f.read(reinterpret_cast<char*>(&n), 8);
      sh.keys.resize(n);
      f.read(reinterpret_cast<char*>(sh.keys.data()), n * 8);
      sh.w.resize(n * t->dim);
      f.read(reinterpret_cast<char*>(sh.w.data()),
             n * t->dim * sizeof(float));
      sh.state.resize(n * t->dim * spd);
      f.read(reinterpret_cast<char*>(sh.state.data()),
             n * t->dim * spd * sizeof(float));
      sh.index.clear();
      for (uint64_t i = 0; i < n; ++i) sh.index[sh.keys[i]] = i;
    }
  }
  respond(fd, 0, nullptr, 0);
}

void serve_conn(Server& srv, int fd) {
  struct Scope {
    Server& s;
    ~Scope() { s.active_conns.fetch_sub(1); }
  } scope{srv};
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  for (;;) {
    char hdr[13];
    if (!read_full(fd, hdr, 13)) break;
    uint8_t op = static_cast<uint8_t>(hdr[0]);
    uint32_t table;
    uint64_t nbytes;
    std::memcpy(&table, hdr + 1, 4);
    std::memcpy(&nbytes, hdr + 5, 8);
    if (nbytes > (1ULL << 31)) {      // 2 GiB request cap
      respond_err(fd, "request too large");
      break;
    }
    std::vector<char> body(nbytes);
    if (nbytes && !read_full(fd, body.data(), nbytes)) break;

    switch (op) {
      case kPing:
        respond(fd, 0, "pong", 4);
        break;
      case kCreateTable:
        handle_create(srv, body, table, fd);
        break;
      case kPullDense: {
        DenseTable* t = srv.dense_at(table);
        if (!t) { respond_err(fd, "no dense table"); break; }
        std::lock_guard<std::mutex> g(t->mu);
        respond(fd, 0, t->w.data(), t->w.size() * sizeof(float));
        break;
      }
      case kPushDense:
      case kPushDenseGrad: {
        DenseTable* t = srv.dense_at(table);
        if (!t) { respond_err(fd, "no dense table"); break; }
        std::lock_guard<std::mutex> g(t->mu);
        if (body.size() != t->w.size() * sizeof(float)) {
          respond_err(fd, "dense size mismatch");
          break;
        }
        const float* vals = reinterpret_cast<const float*>(body.data());
        if (op == kPushDense) {
          std::memcpy(t->w.data(), vals, body.size());
        } else {
          t->step += 1;
          uint64_t spd = slots_per_dim(t->opt.kind);
          for (uint64_t r = 0; r < t->rows; ++r)
            apply_row(t->opt, &t->w[r * t->dim],
                      spd ? &t->state[r * t->dim * spd] : nullptr,
                      &vals[r * t->dim], t->dim, t->step);
        }
        respond(fd, 0, nullptr, 0);
        break;
      }
      case kPullSparse: {
        SparseTable* t = srv.sparse_at(table);
        if (!t) { respond_err(fd, "no sparse table"); break; }
        handle_pull_sparse(*t, body, fd);
        break;
      }
      case kAddSparse: {
        SparseTable* t = srv.sparse_at(table);
        if (!t) { respond_err(fd, "no sparse table"); break; }
        handle_add_sparse(*t, body, fd);
        break;
      }
      case kPushSparseGrad:
      case kPushSparse: {
        SparseTable* t = srv.sparse_at(table);
        if (!t) { respond_err(fd, "no sparse table"); break; }
        handle_push_sparse(*t, body, op == kPushSparseGrad, fd);
        break;
      }
      case kSave:
        handle_save(srv, body, table, fd);
        break;
      case kLoad:
        handle_load(srv, body, table, fd);
        break;
      case kStats: {
        uint64_t n = 0;
        if (SparseTable* t = srv.sparse_at(table)) {
          for (auto& sh : t->shards) {
            std::lock_guard<std::mutex> g(sh.mu);
            n += sh.keys.size();
          }
        } else if (DenseTable* t = srv.dense_at(table)) {
          n = t->rows;
        }
        respond(fd, 0, &n, 8);
        break;
      }
      case kKind: {
        uint8_t k = 2;                 // absent
        if (srv.dense_at(table)) k = 0;
        else if (srv.sparse_at(table)) k = 1;
        respond(fd, 0, &k, 1);
        break;
      }
      case kStop:
        respond(fd, 0, nullptr, 0);
        srv.stop.store(true);
        // unblock the accept() loop so the process can exit
        ::shutdown(srv.listen_fd, SHUT_RDWR);
        ::close(fd);
        return;
      default:
        respond_err(fd, "bad op");
    }
    if (srv.stop.load()) break;
  }
  ::close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  int port = argc > 1 ? std::atoi(argv[1]) : 0;
  const char* host = argc > 2 ? argv[2] : "127.0.0.1";
  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    std::fprintf(stderr, "bad bind address %s\n", host);
    return 1;
  }
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    std::perror("bind");
    return 1;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(lfd, reinterpret_cast<sockaddr*>(&addr), &alen);
  ::listen(lfd, 64);
  // readiness line consumed by the python launcher
  std::printf("PS_SERVER_READY %d\n", ntohs(addr.sin_port));
  std::fflush(stdout);

  // heap-allocated and never deleted: detached connection threads may
  // still hold the reference at exit; _Exit below skips destructors
  Server& srv = *new Server();
  srv.listen_fd = lfd;
  while (!srv.stop.load()) {
    int cfd = ::accept(lfd, nullptr, nullptr);
    if (cfd < 0) break;
    if (srv.stop.load()) {
      ::close(cfd);
      break;
    }
    srv.active_conns.fetch_add(1);
    // detached: long-lived servers must not accumulate joinable zombies;
    // shutdown waits on the active counter below
    std::thread([&srv, cfd] { serve_conn(srv, cfd); }).detach();
  }
  ::close(lfd);
  for (int i = 0; i < 500 && srv.active_conns.load() > 0; ++i)
    std::this_thread::sleep_for(std::chrono::milliseconds(10));
  std::_Exit(0);   // immediate: no destructor races with lingering threads
}
