"""Parameter-server runtime: host-resident sparse tables.

reference parity: the PS stack (paddle/fluid/distributed/ ~22k LoC C++:
brpc server/client, SparseTable shards, async push/pull;
python/paddle/distributed/fleet in PS mode with
role_maker/init_server/init_worker). Its job: embedding tables far larger
than accelerator memory, updated sparsely.

TPU-native redesign, two tiers:
 - in-process host-memory `SparseTable` (this file): on TPU pods the
   first "server" is the host RAM attached to every worker (hundreds of
   GB) — pull gathers rows to device, push applies the sparse optimizer
   host-side;
 - a REAL process model (`service.py` + `_native/ps_server.cpp`): C++
   server processes hosting dense+sparse tables over TCP, a python
   `PSClient` with client-side key sharding across servers, and an
   `AsyncCommunicator` background sender — the reference's
   brpc_ps_server/communicator pair rebuilt lean.
DistributedEmbedding wires pull into forward and push into the backward
tape over either backend, so training code sees an ordinary Layer while
gradients stream to host/remote memory — the reference's async
push/pull becomes the natural eager flow.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import TapeNode, Tensor, _wrap_outputs, is_grad_enabled
from ...nn.layer import Layer

__all__ = ["SparseTable", "SSDSparseTable", "DistributedEmbedding",
           "GraphTable", "GraphService", "GraphClient",
           "PSClient", "PSServerHandle", "AsyncCommunicator",
           "GeoCommunicator", "run_server", "role_from_env",
           "server_endpoints_from_env"]

from .graph import GraphClient, GraphService, GraphTable  # noqa: E402
from .service import (AsyncCommunicator, GeoCommunicator,  # noqa: E402
                      PSClient, PSServerHandle, role_from_env, run_server,
                      server_endpoints_from_env)
from .ssd_table import SSDSparseTable  # noqa: E402


class SparseTable:
    """Host-memory embedding shard with sparse optimizers.

    reference: fluid/distributed SparseTable + DownpourWorker push/pull;
    optimizers follow the PS convention (sgd | adagrad, applied row-wise
    on push).
    """

    def __init__(self, num_rows: int, dim: int, initializer=None,
                 optimizer: str = "adagrad", lr: float = 0.05,
                 shard_id: int = 0, num_shards: int = 1, seed: int = 0):
        self.num_rows = num_rows
        self.dim = dim
        self.shard_id = shard_id
        self.num_shards = num_shards
        # each shard stores ONLY its rows (ids with id % num_shards ==
        # shard_id): that is the whole point of sharding a
        # bigger-than-one-host table
        self.local_rows = (num_rows + num_shards - 1 - shard_id) \
            // num_shards
        rng = np.random.default_rng(seed + shard_id)
        scale = 1.0 / np.sqrt(dim)
        self.data = (initializer(self.local_rows, dim)
                     if initializer is not None
                     else rng.uniform(-scale, scale,
                                      (self.local_rows, dim))
                     .astype(np.float32))
        self.optimizer = optimizer
        self.lr = lr
        if optimizer == "adagrad":
            self._g2 = np.zeros((self.local_rows,), np.float32)
        elif optimizer != "sgd":
            raise ValueError(f"unknown PS optimizer {optimizer!r}")
        self.pull_count = 0
        self.push_count = 0

    def _local(self, ids: np.ndarray) -> np.ndarray:
        if self.num_shards > 1:
            if not ((ids % self.num_shards) == self.shard_id).all():
                raise ValueError("ids routed to the wrong shard")
            return ids // self.num_shards
        return ids

    def pull(self, ids) -> np.ndarray:
        """Gather rows for ids (reference: pull_sparse)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        self.pull_count += 1
        return self.data[self._local(ids)]

    def push(self, ids, grads) -> None:
        """Apply a sparse update for ids (reference: push_sparse).
        Duplicate ids accumulate before the update, matching dense
        embedding-gradient semantics."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        local = self._local(ids)
        uniq, inv = np.unique(local, return_inverse=True)
        acc = np.zeros((len(uniq), self.dim), np.float32)
        np.add.at(acc, inv, grads)
        if self.optimizer == "adagrad":
            self._g2[uniq] += (acc ** 2).mean(axis=1)
            denom = np.sqrt(self._g2[uniq])[:, None] + 1e-10
            self.data[uniq] -= self.lr * acc / denom
        else:
            self.data[uniq] -= self.lr * acc
        self.push_count += 1

    # -- raw row access (tier promotion/demotion; no optimizer step) -------
    def read_rows(self, ids):
        """(vecs [n, dim], g2 [n]) WITHOUT counting a pull — the tier
        manager's raw read when promoting rows into a faster tier."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        local = self._local(ids)
        g2 = (self._g2[local].copy() if self.optimizer == "adagrad"
              else np.zeros(len(local), np.float32))
        return self.data[local].copy(), g2

    def write_rows(self, ids, vecs, g2=None) -> None:
        """Overwrite rows (and optimizer state) verbatim — the tier
        manager's demotion write-back. NOT a push: no gradient math."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        local = self._local(ids)
        self.data[local] = np.asarray(vecs, np.float32).reshape(
            len(local), self.dim)
        if self.optimizer == "adagrad" and g2 is not None:
            self._g2[local] = np.asarray(g2, np.float32).reshape(-1)

    # -- checkpoint --------------------------------------------------------
    def state_dict(self) -> Dict[str, np.ndarray]:
        out = {"data": self.data}
        if self.optimizer == "adagrad":
            out["g2"] = self._g2
        return out

    def load_state_dict(self, state):
        self.data = np.asarray(state["data"], np.float32)
        if self.optimizer == "adagrad" and "g2" in state:
            self._g2 = np.asarray(state["g2"], np.float32)


class DistributedEmbedding(Layer):
    """Embedding whose table lives in host memory (PS-style).

    forward: host pull -> device array; backward: the tape node pushes the
    row gradients straight into the table (fused server update — the
    reference's async push). The table is NOT a Parameter: dense
    optimizers skip it, exactly like the reference's PS-mode embeddings.

    Two backends:
      - in-process `SparseTable` (default): host RAM of this worker;
      - a remote PS service via `client=PSClient(...)` + `table_id=`:
        rows pulled over TCP from the C++ server processes
        (ps.service / _native/ps_server.cpp); gradients pushed either
        synchronously or through an `AsyncCommunicator` (reference's
        async-SGD mode, communicator.cc).
    """

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 table: Optional[SparseTable] = None, lr: float = 0.05,
                 optimizer: str = "adagrad", name=None, client=None,
                 table_id: int = 0, communicator=None):
        super().__init__()
        self.client = client
        self.table_id = table_id
        self.communicator = communicator
        if client is None:
            self.table = table or SparseTable(num_embeddings, embedding_dim,
                                              optimizer=optimizer, lr=lr)
        else:
            self.table = None
        self.embedding_dim = embedding_dim

    def _pull(self, ids_np: np.ndarray) -> np.ndarray:
        flat = ids_np.reshape(-1)
        if self.client is not None:
            return self.client.pull_sparse(self.table_id,
                                           flat.astype(np.uint64),
                                           self.embedding_dim)
        return self.table.pull(flat)

    def _push(self, ids_np: np.ndarray, grads: np.ndarray) -> None:
        flat = ids_np.reshape(-1).astype(np.uint64)
        g = grads.reshape(len(flat), self.embedding_dim)
        if self.client is None:
            self.table.push(ids_np.reshape(-1), g)
        elif self.communicator is not None:
            self.communicator.push_sparse_grad(self.table_id, flat, g)
        else:
            self.client.push_sparse(self.table_id, flat, g, grad=True)

    def forward(self, ids: Tensor) -> Tensor:
        from ...core.tensor import _is_tracer
        raw = ids._data if isinstance(ids, Tensor) else ids
        if _is_tracer(raw):
            raise RuntimeError(
                "DistributedEmbedding pulls from HOST memory and is "
                "eager-only; keep it outside jit/TrainStep (feed its "
                "output as a batch input), like the reference's PS-mode "
                "embeddings which live outside the trainer program")
        ids_np = np.asarray(raw)
        rows = self._pull(ids_np)
        out = jnp.asarray(rows.reshape(ids_np.shape + (self.embedding_dim,)))
        node = None
        if is_grad_enabled():
            push = self._push

            def vjp_fn(g, ids_np=ids_np):
                push(ids_np, np.asarray(g))
                return ()                  # no upstream tensors

            node = TapeNode(vjp_fn, [],
                            [jax.ShapeDtypeStruct(out.shape, out.dtype)],
                            name="ps_embedding")
        return _wrap_outputs(out, node=node)
