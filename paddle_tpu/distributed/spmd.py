"""SPMD plumbing: mesh construction + axis-aware shard_map.

The TPU-native replacement for the reference's multi-process execution
fabric: where the reference launches one process per device and wires NCCL
rings (fleet/launch_utils.py, platform/nccl_helper.h), here a single
controller lays a :class:`jax.sharding.Mesh` over the chips and jit-compiles
SPMD programs; collectives inside are keyed by named mesh axes.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import env

__all__ = ["make_mesh", "shard_map", "named_sharding", "current_mesh",
           "PartitionSpec", "apply_param_shardings"]

PartitionSpec = P


def make_mesh(axis_sizes: Dict[str, int], devices=None) -> Mesh:
    """Build a named mesh. Axis order = dict order; trailing axes are most
    minor (place tp/sp last so their collectives ride adjacent ICI links —
    see SURVEY.md §7 design mapping)."""
    names = tuple(axis_sizes.keys())
    sizes = tuple(int(v) for v in axis_sizes.values())
    n = int(np.prod(sizes))
    devices = list(devices if devices is not None else jax.devices())
    if n > len(devices):
        raise ValueError(
            f"mesh {dict(axis_sizes)} needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(sizes)
    mesh = Mesh(arr, names)
    return mesh


def current_mesh() -> Optional[Mesh]:
    return env.get_mesh()


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def apply_param_shardings(layer, mesh: Optional[Mesh] = None):
    """Lay a Layer's parameters out on the mesh per their PartitionSpecs.

    The TPU-native replacement for the reference's parameter broadcast at
    engine setup (fleet/utils/hybrid_parallel_util.py:103): instead of
    broadcasting replicas over NCCL, each Parameter carries a
    ``spec`` (PartitionSpec) and is device_put once; XLA keeps it resident
    in the sharded layout from then on.
    """
    mesh = mesh or env.get_mesh()
    if mesh is None:
        raise ValueError("no active mesh; call fleet.init or pass mesh=")
    for _, p in layer.named_parameters():
        spec = getattr(p, "spec", None) or P()
        p._data = jax.device_put(p._data, NamedSharding(mesh, spec))
    for _, b in layer.named_buffers():
        b._data = jax.device_put(b._data, NamedSharding(mesh, P()))
    return layer


def shard_map(body, mesh: Mesh, in_specs, out_specs, check_vma: bool = False):
    """jax.shard_map wrapper that records the mesh's axis names as *bound*
    for the dynamic extent of the body trace, so paddle_tpu.distributed
    collectives called inside dispatch to their lax (traced) lowering."""

    def wrapped(*args):
        with env.axes_bound(*mesh.axis_names):
            return body(*args)

    return jax.shard_map(wrapped, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=check_vma)
