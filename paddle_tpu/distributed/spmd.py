"""SPMD plumbing: mesh construction + axis-aware shard_map.

The TPU-native replacement for the reference's multi-process execution
fabric: where the reference launches one process per device and wires NCCL
rings (fleet/launch_utils.py, platform/nccl_helper.h), here a single
controller lays a :class:`jax.sharding.Mesh` over the chips and jit-compiles
SPMD programs; collectives inside are keyed by named mesh axes.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import env

__all__ = ["make_mesh", "shard_map", "named_sharding", "current_mesh",
           "PartitionSpec", "apply_param_shardings", "constrain", "BATCH",
           "data_axes", "degrade_spec", "SERVE_KV_SPEC",
           "shard_serving_cache"]

PartitionSpec = P

# Sentinel for "the batch dimension": expands to every data-style mesh axis
# present (dp and the ZeRO 'sharding' axis), matching the composite
# P(('dp', 'sharding')) batch layout TrainStep uses for its data_spec.
BATCH = "__batch__"
_DATA_AXES = ("dp", "sharding")


def data_axes(mesh: Mesh):
    """The mesh axes the batch dim is sharded over (dp + ZeRO sharding)."""
    return tuple(a for a in _DATA_AXES if a in mesh.axis_names)


def _degrade_entry(s, names):
    """One PartitionSpec entry with axis names absent from ``names``
    degraded to None/dropped (replicated) — the shared rule behind
    :func:`constrain`, :func:`apply_param_shardings` and TrainStep's
    ``_param_specs``: a model annotated for mp/ep composes with any
    sub-mesh that lacks those axes."""
    if isinstance(s, str):
        return s if s in names else None
    if isinstance(s, (tuple, list)):
        kept = tuple(a for a in s if a in names)
        return kept if kept else None
    return s


def degrade_spec(spec, mesh: Mesh) -> P:
    """A full PartitionSpec with absent-axis entries degraded for
    ``mesh`` (no BATCH sentinel handling — that is constrain-only)."""
    names = set(mesh.axis_names)
    return P(*(_degrade_entry(s, names) for s in tuple(spec)))


def constrain(x, *spec):
    """with_sharding_constraint on a Tensor/array against the active mesh.

    Axis names absent from the mesh degrade to None (replicated); the BATCH
    sentinel expands to the composite data axes; trailing dims pad with
    None. No-op without an active mesh — model code can sprinkle layout
    pins unconditionally.
    """
    mesh = env.get_mesh()
    if mesh is None:
        return x
    names = set(mesh.axis_names)

    def clean_one(s):
        if s == BATCH:
            axes = data_axes(mesh)
            return axes if axes else None
        return _degrade_entry(s, names)
    clean = tuple(clean_one(s) for s in spec)
    ndim = len(x.shape)
    clean = clean[:ndim] + (None,) * max(0, ndim - len(clean))
    sh = NamedSharding(mesh, P(*clean))
    from ..core.tensor import Tensor, apply
    if isinstance(x, Tensor):
        return apply(lambda a: jax.lax.with_sharding_constraint(a, sh), x,
                     name="sharding_constraint")
    return jax.lax.with_sharding_constraint(x, sh)


def make_mesh(axis_sizes: Dict[str, int], devices=None) -> Mesh:
    """Build a named mesh. Axis order = dict order; trailing axes are most
    minor (place tp/sp last so their collectives ride adjacent ICI links —
    see SURVEY.md §7 design mapping)."""
    names = tuple(axis_sizes.keys())
    sizes = tuple(int(v) for v in axis_sizes.values())
    n = int(np.prod(sizes))
    devices = list(devices if devices is not None else jax.devices())
    if n > len(devices):
        raise ValueError(
            f"mesh {dict(axis_sizes)} needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(sizes)
    mesh = Mesh(arr, names)
    return mesh


def current_mesh() -> Optional[Mesh]:
    return env.get_mesh()


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def apply_param_shardings(layer, mesh: Optional[Mesh] = None):
    """Lay a Layer's parameters out on the mesh per their PartitionSpecs.

    The TPU-native replacement for the reference's parameter broadcast at
    engine setup (fleet/utils/hybrid_parallel_util.py:103): instead of
    broadcasting replicas over NCCL, each Parameter carries a
    ``spec`` (PartitionSpec) and is device_put once; XLA keeps it resident
    in the sharded layout from then on.
    """
    mesh = mesh or env.get_mesh()
    if mesh is None:
        raise ValueError("no active mesh; call fleet.init or pass mesh=")
    for _, p in layer.named_parameters():
        spec = getattr(p, "spec", None) or P()
        p._data = jax.device_put(
            p._data, NamedSharding(mesh, degrade_spec(spec, mesh)))
    for _, b in layer.named_buffers():
        b._data = jax.device_put(b._data, NamedSharding(mesh, P()))
    return layer


_TP_COLUMN = ("q_proj.weight", "k_proj.weight", "v_proj.weight",
              "linear1.weight")          # [in, out]: shard out over mp
_TP_ROW = ("out_proj.weight", "linear2.weight")   # [in, out]: shard in
_TP_COLUMN_BIAS = ("q_proj.bias", "k_proj.bias", "v_proj.bias",
                   "linear1.bias")
_VOCAB = ("word_embeddings.weight",)


def apply_hybrid_specs(layer, mp_axis: str = "mp"):
    """Stamp Megatron-style tensor-parallel PartitionSpecs onto a model
    built from nn.MultiHeadAttention/TransformerEncoder by parameter-name
    pattern (reference: the mp_layers rewrite the reference applies when
    building hybrid models — here layout is declarative so stock layers
    become TP-sharded without rewriting the model).

    Column-parallel (out-dim sharded): q/k/v projections, ffn in-proj.
    Row-parallel (in-dim sharded): attention out-proj, ffn out-proj — XLA
    inserts the psum after it. Vocab embeddings shard over the vocab dim.
    Everything else (norms, biases of row layers) stays replicated.
    """
    for name, p in layer.named_parameters():
        if getattr(p, "spec", None) not in (None, P()):
            continue                          # already placed explicitly
        if name.endswith(_VOCAB):
            p.spec = P(mp_axis, None)
        elif name.endswith(_TP_COLUMN):
            p.spec = P(None, mp_axis)
        elif name.endswith(_TP_ROW):
            p.spec = P(mp_axis, None)
        elif name.endswith(_TP_COLUMN_BIAS):
            p.spec = P(mp_axis)
        else:
            p.spec = P()
    return layer


#: layout of a serving paged K/V pool ``[L, P, bs, H, D]`` under tensor
#: parallelism (ISSUE 16): heads shard over the mp axis — the same split
#: apply_hybrid_specs gives the q/k/v projections, so the TP decode
#: program reads/writes its local head shard without any gather. Layers,
#: pages and the per-page token dim stay replicated (page tables index
#: them host-side).
SERVE_KV_SPEC = P(None, None, None, "mp", None)


def shard_serving_cache(cache, mesh: Mesh):
    """Lay a serving PagedKVCache's pools out on the TP mesh (heads over
    ``mp`` per :data:`SERVE_KV_SPEC`, degraded for meshes without an mp
    axis). Called once at engine init, before the first AOT compile, so
    the serving programs see sharded donors and GSPMD keeps the pools
    resident in the split layout — per-chip HBM then holds ``1/mp`` of
    the KV footprint, which is what lets models beyond single-chip HBM
    serve at all."""
    sh = NamedSharding(mesh, degrade_spec(SERVE_KV_SPEC, mesh))
    # quantized pools (FLAGS_serve_kv_quant) are (pages, scales) tuples:
    # the [L, P, bs, H] scale pool shards its heads dim the same way
    sc = NamedSharding(mesh, degrade_spec(P(None, None, None, "mp"), mesh))

    def _put(pool):
        if isinstance(pool, tuple):
            pages, scales = pool
            return (jax.device_put(pages, sh), jax.device_put(scales, sc))
        return jax.device_put(pool, sh)

    cache.k = _put(cache.k)
    cache.v = _put(cache.v)
    return cache


def shard_map(body, mesh: Mesh, in_specs, out_specs, check_vma: bool = False):
    """jax.shard_map wrapper that records the mesh's axis names as *bound*
    for the dynamic extent of the body trace, so paddle_tpu.distributed
    collectives called inside dispatch to their lax (traced) lowering."""

    def wrapped(*args):
        with env.axes_bound(*mesh.axis_names):
            return body(*args)

    return env.shard_map(wrapped, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=check_vma)
