"""Auto-parallel: annotation-driven sharding.

reference parity: python/paddle/distributed/auto_parallel/ —
ProcessMesh(process_mesh.py:39), shard_tensor(interface.py:34),
shard_op(interface.py:73). The reference records annotations into a
DistributedContext that a partitioner later consumes to rewrite the
static program (partitioner.py, reshard.py).

TPU-native redesign: annotation IS execution. ProcessMesh wraps a
jax.sharding.Mesh; shard_tensor's dims_mapping translates directly to a
PartitionSpec and the tensor is device_put (or constraint-pinned inside a
trace) immediately — GSPMD is the partitioner, so the reference's
completion/partition/reshard machinery (~15k LoC) collapses into layout
declarations.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor, apply
from .. import env

__all__ = ["ProcessMesh", "shard_tensor", "shard_op", "reshard",
           "get_mesh"]


class ProcessMesh:
    """Logical process topology (reference: process_mesh.py:39).

    mesh: nested list of process ids (its SHAPE defines the topology) or a
    shape tuple; dim_names default to d0..dn. Becomes the active
    jax.sharding.Mesh over real devices in row-major order.
    """

    def __init__(self, mesh, dim_names: Optional[Sequence[str]] = None,
                 process_ids=None):
        arr = np.asarray(mesh)
        if arr.ndim == 0:
            raise ValueError("mesh must be at least 1-D")
        self.topology = list(arr.shape)
        self.process_ids = [int(i) for i in arr.reshape(-1)]
        self.dim_names = list(dim_names) if dim_names else \
            [f"d{i}" for i in range(arr.ndim)]
        n = int(np.prod(self.topology))
        devices = jax.devices()
        if n > len(devices):
            raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
        bad = [i for i in self.process_ids if i >= len(devices) or i < 0]
        if bad:
            raise ValueError(
                f"process ids {bad} out of range (have {len(devices)} "
                "devices)")
        if len(set(self.process_ids)) != len(self.process_ids):
            raise ValueError("duplicate process ids in mesh")
        ordered = [devices[i] for i in self.process_ids]
        self.mesh = Mesh(np.array(ordered).reshape(self.topology),
                         tuple(self.dim_names))

    @property
    def shape(self):
        return self.topology

    def __enter__(self):
        self._prev = env.get_mesh()
        env.set_mesh(self.mesh)
        return self

    def __exit__(self, *exc):
        env.set_mesh(self._prev)


def _spec_from_dims_mapping(dim_names: Sequence[str], dims_mapping,
                            ndim: int) -> P:
    """dims_mapping[i] = mesh dim that splits tensor dim i (-1 = none);
    short mappings pad replicated."""
    dm = list(dims_mapping if dims_mapping is not None else [-1] * ndim)
    dm += [-1] * (ndim - len(dm))
    return P(*[None if m == -1 else dim_names[m] for m in dm])


def shard_tensor(x, dist_attr: Optional[Dict] = None, process_mesh=None,
                 dims_mapping=None):
    """Annotate-and-place a tensor (reference: interface.py:34).

    Accepts the reference dict form ({"process_mesh": ..., "dims_mapping":
    [...]}) or explicit kwargs. Concrete tensors are device_put into the
    sharded layout at once; traced values get a sharding constraint.
    """
    if dist_attr:
        process_mesh = dist_attr.get("process_mesh", process_mesh)
        dims_mapping = dist_attr.get("dims_mapping", dims_mapping)
    mesh, dim_names = _resolve_mesh(process_mesh)
    t = x if isinstance(x, Tensor) else Tensor(jax.numpy.asarray(x))
    spec = _spec_from_dims_mapping(dim_names, dims_mapping, len(t.shape))
    sharding = NamedSharding(mesh, spec)

    from ...core.tensor import _is_tracer
    if _is_tracer(t._data):
        return apply(lambda a: jax.lax.with_sharding_constraint(a, sharding),
                     t, name="shard_tensor")
    t._data = jax.device_put(t._data, sharding)
    if hasattr(t, "spec"):
        t.spec = spec
    return t


def shard_op(op_fn, dist_attr: Optional[Dict] = None):
    """Wrap a callable so its Tensor inputs/outputs get the annotated
    layouts (reference: interface.py:73). Per-input specs use the same
    dict keys (the input objects) as the reference; outputs take the
    op-level process_mesh with unspecified dims replicated."""
    dist_attr = dist_attr or {}
    pmesh = dist_attr.get("process_mesh")
    if pmesh is not None and not isinstance(pmesh, ProcessMesh):
        pmesh = ProcessMesh(pmesh)

    def wrapped(*args, **kwargs):
        placed = []
        for i, a in enumerate(args):
            # per-input specs: keyed by the Tensor OBJECT (reference form,
            # matches only those exact tensors) or by POSITION (robust for
            # wrap-once-call-many)
            attr = dist_attr.get(i)
            if attr is None and isinstance(a, Tensor):
                attr = dist_attr.get(a)
            if attr is not None and pmesh is not None:
                placed.append(shard_tensor(
                    a, process_mesh=pmesh,
                    dims_mapping=attr.get("dims_mapping")))
            else:
                placed.append(a)
        return op_fn(*placed, **kwargs)

    return wrapped


def _resolve_mesh(process_mesh):
    if process_mesh is None:
        mesh = env.get_mesh()
        if mesh is None:
            raise ValueError("no target mesh: pass process_mesh= or enter "
                             "a `with ProcessMesh(...):` block")
        return mesh, list(mesh.axis_names)
    if isinstance(process_mesh, ProcessMesh):
        return process_mesh.mesh, process_mesh.dim_names
    if isinstance(process_mesh, Mesh):
        return process_mesh, list(process_mesh.axis_names)
    pm = ProcessMesh(process_mesh)
    return pm.mesh, pm.dim_names


def reshard(x, process_mesh=None, dims_mapping=None, spec=None):
    """Runtime redistribution of a (possibly sharded) tensor onto an
    arbitrary target mesh/layout.

    reference parity: auto_parallel/reshard.py:1 Resharder — the program
    pass that inserts split/concat/send/recv ops to move a tensor between
    two distributed layouts. TPU-native: the source layout is whatever
    the array currently carries; ``jax.device_put`` onto the target
    ``NamedSharding`` computes the minimal redistribution (XLA collectives
    for same-mesh moves, device-to-device copies across meshes). Works
    between DIFFERENT meshes — different axis names, shapes, or device
    orders — not just within one; that is the piece checkpoint
    reshard-on-load alone did not cover.

    ``spec`` takes a PartitionSpec directly; ``dims_mapping`` accepts the
    reference's [-1, 0, ...] form. Eager-only (a traced value cannot
    change mesh mid-program; use shard_tensor's constraint inside jit).
    """
    mesh, dim_names = _resolve_mesh(process_mesh)
    t = x if isinstance(x, Tensor) else Tensor(jax.numpy.asarray(x))
    if spec is None:
        spec = _spec_from_dims_mapping(dim_names, dims_mapping,
                                       len(t.shape))
    sharding = NamedSharding(mesh, spec)
    from ...core.tensor import _is_tracer
    if _is_tracer(t._data):
        raise ValueError(
            "reshard is a runtime redistribution and cannot run on traced "
            "values — inside jit use shard_tensor (a sharding "
            "constraint on the CURRENT mesh)")
    t._data = jax.device_put(t._data, sharding)
    if hasattr(t, "spec"):
        t.spec = spec
    return t


def get_mesh():
    return env.get_mesh()
