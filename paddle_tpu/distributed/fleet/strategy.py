"""DistributedStrategy: one typed config for every parallelism feature.

reference: python/paddle/distributed/fleet/base/distributed_strategy.py
backed by framework/distributed_strategy.proto:176-243. Here a plain python
config object (no proto) with the same feature axes; meta-optimizer program
rewrites become sharding specs + function transforms (SURVEY.md §7), so most
knobs configure those transforms.
"""

from __future__ import annotations

import copy
from typing import Any, Dict


_DEFAULTS: Dict[str, Any] = {
    # hybrid parallelism degrees (reference: hybrid_configs → topology.py:36)
    "hybrid_configs": {
        "dp_degree": 1,
        "mp_degree": 1,
        "pp_degree": 1,
        "sharding_degree": 1,
        "sp_degree": 1,
    },
    # AMP (reference: distributed_strategy.proto amp_configs)
    "amp": False,
    "amp_configs": {
        "init_loss_scaling": 32768.0,
        "incr_every_n_steps": 1000,
        "decr_every_n_nan_or_inf": 2,
        "incr_ratio": 2.0,
        "decr_ratio": 0.5,
        "use_pure_fp16": False,
        "use_bf16": True,  # TPU-native default
        "custom_white_list": [],
        "custom_black_list": [],
    },
    # recompute (reference: recompute_configs)
    "recompute": False,
    "recompute_configs": {"checkpoints": []},
    # ZeRO-style sharding (reference: sharding_configs)
    "sharding": False,
    "sharding_configs": {"stage": 1, "sharding_degree": 1},
    # pipeline (reference: pipeline_configs)
    "pipeline": False,
    "pipeline_configs": {"accumulate_steps": 1, "micro_batch_size": 1,
                         "schedule_mode": "1F1B"},
    # tensor parallel (reference: tensor_parallel_configs)
    "tensor_parallel": False,
    "tensor_parallel_configs": {"tensor_parallel_degree": 1},
    # gradient merge / accumulation
    "gradient_merge": False,
    "gradient_merge_configs": {"k_steps": 1, "avg": True},
    # misc knobs kept for parity
    "find_unused_parameters": False,
    "fuse_all_reduce_ops": True,       # XLA fuses; parity no-op
    "fuse_grad_size_in_MB": 32,        # parity no-op
    "nccl_comm_num": 1,                # parity no-op
    "localsgd": False,
    "localsgd_configs": {"k_steps": 1, "begin_step": 1},
    "adaptive_localsgd": False,
    "adaptive_localsgd_configs": {"init_k_steps": 1, "begin_step": 1},
    "dgc": False,
    "lamb": False,
    "lars": False,
    "a_sync": False,
}


class DistributedStrategy:
    """reference: fleet/base/distributed_strategy.py DistributedStrategy —
    property per proto field; here attributes over a defaults dict."""

    def __init__(self):
        self.__dict__["_config"] = copy.deepcopy(_DEFAULTS)

    def __getattr__(self, name):
        cfg = self.__dict__["_config"]
        if name in cfg:
            return cfg[name]
        raise AttributeError(f"DistributedStrategy has no field {name!r}")

    def __setattr__(self, name, value):
        cfg = self.__dict__["_config"]
        if name not in cfg:
            raise AttributeError(f"DistributedStrategy has no field {name!r}")
        # localsgd/adaptive_localsgd are wired end-to-end (reference:
        # fleet/meta_optimizers/localsgd_optimizer.py): an optimizer
        # wrapped by fleet.distributed_optimizer under this strategy makes
        # TrainStep build a LocalSGDTrainStep — k local steps per replica
        # (shard_map, zero ICI traffic) then one parameter pmean;
        # adaptive=True gives the AdaComm schedule.
        if name == "dgc" and value:
            raise NotImplementedError(
                "dgc (deep gradient compression) is not implemented: it "
                "exists to shrink gradient traffic over slow networks; "
                "TPU ICI allreduce bandwidth makes it counterproductive — "
                "use data parallelism as-is, or bf16 params (amp O2) to "
                "halve collective bytes")
        if isinstance(cfg[name], dict) and isinstance(value, dict):
            cfg[name].update(value)
        else:
            cfg[name] = value

    def to_dict(self) -> Dict[str, Any]:
        return copy.deepcopy(self.__dict__["_config"])

    def __repr__(self):
        lines = ["DistributedStrategy("]
        for k, v in self.__dict__["_config"].items():
            lines.append(f"  {k}={v!r},")
        lines.append(")")
        return "\n".join(lines)
