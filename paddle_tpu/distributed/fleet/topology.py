"""Hybrid-parallel topology: rank mesh → jax.sharding.Mesh + per-axis groups.

reference: python/paddle/distributed/fleet/base/topology.py —
CommunicateTopology (:36) models the cartesian rank mesh over axes
[data, pipe, sharding, model]; HybridCommunicateGroup (:117) builds one NCCL
comm group per axis (_set_comm_group:193) plus p2p next/prev pipe groups
(:225).

TPU-native: the rank mesh IS a `jax.sharding.Mesh` over real devices; a
"comm group" is just a named axis (no comm-id bootstrap). Axis order places
mp (then sp) most-minor so tensor-parallel collectives ride adjacent ICI
links, dp outermost so data-parallel allreduce crosses the slowest links
(SURVEY.md §7 design mapping; scaling-book recipe).
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from .. import env
from ..collective import Group, new_group
from ..spmd import make_mesh

__all__ = ["CommunicateTopology", "HybridCommunicateGroup",
           "get_hybrid_communicate_group", "set_hybrid_communicate_group",
           "MeshTopologyError", "validate_topology"]

# mesh axis order: outermost → innermost
_AXIS_ORDER = ("dp", "pp", "sharding", "sp", "mp")


class MeshTopologyError(ValueError):
    """The requested hybrid-parallel degrees do not factor the visible
    device count. Raised by name at ``fleet.init`` /
    ``HybridCommunicateGroup`` instead of the shape error a mismatched
    mesh used to hit deep inside ``make_mesh``."""


def validate_topology(degrees: Dict[str, int], n_devices: int) -> int:
    """Validate that the axis degrees exactly factor ``n_devices``.

    The product must be positive and DIVIDE the visible device count (a
    sub-mesh over a device prefix is legal — tests pin pp-only meshes on
    8-device hosts); a product that exceeds the device count, divides
    nothing, or contains a non-positive degree raises
    :class:`MeshTopologyError` naming the offending configuration.
    Returns the product."""
    bad = {k: v for k, v in degrees.items() if int(v) < 1}
    if bad:
        raise MeshTopologyError(
            f"hybrid-parallel degrees must be >= 1, got {bad} "
            f"(full config: {dict(degrees)})")
    n = int(np.prod([int(v) for v in degrees.values()])) if degrees else 1
    desc = "x".join(f"{k}{int(v)}" for k, v in degrees.items())
    if n > n_devices:
        raise MeshTopologyError(
            f"mesh {desc} needs {n} devices, but only {n_devices} are "
            "visible. Lower a degree, or expose more devices "
            "(XLA_FLAGS=--xla_force_host_platform_device_count=N for "
            "virtual CPU meshes).")
    if n_devices % n:
        raise MeshTopologyError(
            f"mesh {desc} ({n} ranks) does not factor the {n_devices} "
            f"visible devices ({n_devices} % {n} = {n_devices % n}): "
            "every device must belong to exactly one rank position or "
            "sit in an unused tail that the used prefix tiles evenly. "
            "Pick degrees whose product divides the device count, or "
            "pass an explicit devices= prefix of the right length to "
            "HybridCommunicateGroup.")
    return n


class CommunicateTopology:
    """Cartesian rank-coordinate math (reference: topology.py:36)."""

    def __init__(self, hybrid_group_names: Sequence[str] = _AXIS_ORDER,
                 dims: Sequence[int] = (1, 1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(int(d) for d in dims)
        self._world = int(np.prod(self._dims))
        coords = list(itertools.product(*(range(d) for d in self._dims)))
        self._coord_of_rank = {r: c for r, c in enumerate(coords)}
        self._rank_of_coord = {c: r for r, c in enumerate(coords)}

    def get_hybrid_group_names(self) -> List[str]:
        return list(self._parallel_names)

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return self._world

    def get_rank(self, **axis_coords) -> int:
        coord = tuple(axis_coords[n] for n in self._parallel_names)
        return self._rank_of_coord[coord]

    def get_coord(self, rank: int) -> Tuple[int, ...]:
        return self._coord_of_rank[rank]

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        """All ranks whose coordinate on `axis_name` equals `index`."""
        ax = self._parallel_names.index(axis_name)
        return sorted(r for r, c in self._coord_of_rank.items()
                      if c[ax] == index)

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """Rank groups that communicate along `axis_name` (reference:
        topology.py get_comm_list — one group per combination of the other
        axes' coordinates)."""
        ax = self._parallel_names.index(axis_name)
        others = [n for i, n in enumerate(self._parallel_names) if i != ax]
        groups = []
        for combo in itertools.product(
                *(range(self._dims[i]) for i in range(len(self._dims))
                  if i != ax)):
            ranks = []
            for k in range(self._dims[ax]):
                coord = list(combo)
                coord.insert(ax, k)
                ranks.append(self._rank_of_coord[tuple(coord)])
            groups.append(ranks)
        return groups


class HybridCommunicateGroup:
    """Builds THE global device mesh + per-axis Group handles.

    reference: topology.py:117 — _set_comm_group per axis via new_group +
    NCCL init; here the mesh is built once and each axis becomes a Group
    carrying the axis name (collectives key on it inside shard_map).
    """

    def __init__(self, topology: Optional[CommunicateTopology] = None,
                 dp_degree: int = 1, mp_degree: int = 1, pp_degree: int = 1,
                 sharding_degree: int = 1, sp_degree: int = 1,
                 devices=None):
        if topology is not None:
            dims = {n: topology.get_dim(n) for n in
                    topology.get_hybrid_group_names()}
            dp_degree = dims.get("dp", 1)
            pp_degree = dims.get("pp", 1)
            sharding_degree = dims.get("sharding", 1)
            sp_degree = dims.get("sp", 1)
            mp_degree = dims.get("mp", 1)
        self._dp_degree = dp_degree
        self._mp_degree = mp_degree
        self._pp_degree = pp_degree
        self._sharding_degree = sharding_degree
        self._sp_degree = sp_degree

        self._topo = CommunicateTopology(
            _AXIS_ORDER,
            (dp_degree, pp_degree, sharding_degree, sp_degree, mp_degree))
        self.nranks = self._topo.world_size()

        devices = list(devices if devices is not None else jax.devices())
        validate_topology(
            {"dp": dp_degree, "pp": pp_degree, "sharding": sharding_degree,
             "sp": sp_degree, "mp": mp_degree}, len(devices))
        self.mesh: Mesh = make_mesh(
            {"dp": dp_degree, "pp": pp_degree, "sharding": sharding_degree,
             "sp": sp_degree, "mp": mp_degree}, devices=devices)
        env.set_mesh(self.mesh)

        # this process's position (single-controller: rank 0's row; in
        # multi-process SPMD each process computes its own)
        self.global_rank = env.get_rank() % self.nranks

        self._groups: Dict[str, Group] = {}
        for ax in _AXIS_ORDER:
            coord = self._topo.get_coord(self.global_rank)
            idx = dict(zip(_AXIS_ORDER, coord))
            ranks = self._topo.get_comm_list(ax)[0]
            # group containing this rank along `ax`
            for grp in self._topo.get_comm_list(ax):
                if self.global_rank in grp:
                    ranks = grp
                    break
            self._groups[ax] = Group(
                ranks, gid=-1 - _AXIS_ORDER.index(ax), axis_name=ax)

    # -- parity accessors (reference: topology.py:117-291) ------------------
    def topology(self) -> CommunicateTopology:
        return self._topo

    def get_global_rank(self) -> int:
        return self.global_rank

    def _axis_rank(self, ax: str) -> int:
        coord = self._topo.get_coord(self.global_rank)
        return coord[_AXIS_ORDER.index(ax)]

    # data parallel
    def get_data_parallel_rank(self) -> int:
        return self._axis_rank("dp")

    def get_data_parallel_world_size(self) -> int:
        return self._dp_degree

    def get_data_parallel_group(self) -> Group:
        return self._groups["dp"]

    def get_data_parallel_group_src_rank(self) -> int:
        return self._groups["dp"].ranks[0]

    # model (tensor) parallel
    def get_model_parallel_rank(self) -> int:
        return self._axis_rank("mp")

    def get_model_parallel_world_size(self) -> int:
        return self._mp_degree

    def get_model_parallel_group(self) -> Group:
        return self._groups["mp"]

    def get_model_parallel_group_src_rank(self) -> int:
        return self._groups["mp"].ranks[0]

    # pipeline
    def get_stage_id(self) -> int:
        return self._axis_rank("pp")

    def get_pipe_parallel_world_size(self) -> int:
        return self._pp_degree

    def get_pipe_parallel_group(self) -> Group:
        return self._groups["pp"]

    def get_p2p_groups(self):
        """Pipeline P2P = ppermute shifts on the pp axis; the Group itself
        is the channel (reference builds next/prev NCCL pairs, :225)."""
        return (self._groups["pp"], self._groups["pp"])

    # sharding (ZeRO)
    def get_sharding_parallel_rank(self) -> int:
        return self._axis_rank("sharding")

    def get_sharding_parallel_world_size(self) -> int:
        return self._sharding_degree

    def get_sharding_parallel_group(self) -> Group:
        return self._groups["sharding"]

    def get_sharding_parallel_group_src_rank(self) -> int:
        return self._groups["sharding"].ranks[0]

    # sequence parallel (TPU-first addition; absent in reference — SURVEY §5)
    def get_sequence_parallel_rank(self) -> int:
        return self._axis_rank("sp")

    def get_sequence_parallel_world_size(self) -> int:
        return self._sp_degree

    def get_sequence_parallel_group(self) -> Group:
        return self._groups["sp"]

    # check parallel mode (reference: _check_vaild_topo / get_parallel_mode)
    def get_parallel_mode(self) -> str:
        if self._pp_degree > 1:
            return "pipeline"
        if self._sharding_degree > 1:
            return "sharding"
        if self._mp_degree > 1:
            return "model"
        if self._dp_degree > 1:
            return "data"
        return "single"


_hcg: Optional[HybridCommunicateGroup] = None


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup):
    global _hcg
    _hcg = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg
