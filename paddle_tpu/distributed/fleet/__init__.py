"""fleet: distributed training facade.

reference: python/paddle/distributed/fleet/base/fleet_base.py:103-1605 —
`fleet.init` boots role maker + hybrid topology, `distributed_model` wraps
the model per parallel mode, `distributed_optimizer` wraps the optimizer
with the meta-optimizer chain (strategy_compiler.py:213).

TPU-native: init builds the device mesh (HybridCommunicateGroup);
distributed_model returns the matching meta_parallel engine (DataParallel /
TensorParallel / PipelineParallel) whose train path is one SPMD jit over the
mesh; meta-optimizer graph rewrites become sharding specs + transforms.
"""

from __future__ import annotations

from typing import Optional

from .. import env
from .dataset import InMemoryDataset, QueueDataset
from .strategy import DistributedStrategy
from .topology import (CommunicateTopology, HybridCommunicateGroup,
                       MeshTopologyError, get_hybrid_communicate_group,
                       set_hybrid_communicate_group, validate_topology)

__all__ = ["init", "DistributedStrategy", "HybridCommunicateGroup",
           "InMemoryDataset", "QueueDataset", "MeshTopologyError",
           "CommunicateTopology", "get_hybrid_communicate_group",
           "validate_topology",
           "distributed_model", "distributed_optimizer", "reset",
           "worker_index", "worker_num", "is_first_worker",
           "barrier_worker", "init_is_called",
           "save_persistables", "load_persistables"]

_fleet_state = {"initialized": False, "strategy": None}


def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None):
    """Initialize the distributed context (reference: fleet_base.py:170).

    Builds the hybrid mesh from strategy.hybrid_configs; with no strategy a
    pure data-parallel mesh over all devices.
    """
    import jax

    from ..parallel import init_parallel_env
    init_parallel_env()

    if strategy is None:
        strategy = DistributedStrategy()
    cfg = strategy.hybrid_configs
    n_dev = len(jax.devices())
    mp = int(cfg.get("mp_degree", 1))
    pp = int(cfg.get("pp_degree", 1))
    sh = int(cfg.get("sharding_degree", 1))
    sp = int(cfg.get("sp_degree", 1))
    dp = int(cfg.get("dp_degree", 0)) or max(1, n_dev // (mp * pp * sh * sp))

    hcg = HybridCommunicateGroup(
        dp_degree=dp, mp_degree=mp, pp_degree=pp,
        sharding_degree=sh, sp_degree=sp)
    set_hybrid_communicate_group(hcg)

    # TP-safe RNG: the 'local_seed' stream folds in the mp rank so dropout
    # masks differ across tensor-parallel shards while 'global_seed' agrees
    # (reference: fleet/meta_parallel/parallel_layers/random.py:32).
    from ...core.random import register_rng_stream
    register_rng_stream("local_seed", 1000 + hcg.get_model_parallel_rank())

    _fleet_state["initialized"] = True
    _fleet_state["strategy"] = strategy
    return


def init_is_called() -> bool:
    return _fleet_state["initialized"]


def _strategy() -> DistributedStrategy:
    if _fleet_state["strategy"] is None:
        _fleet_state["strategy"] = DistributedStrategy()
    return _fleet_state["strategy"]


def worker_index() -> int:
    return env.get_rank()


def worker_num() -> int:
    return env.get_world_size()


def is_first_worker() -> bool:
    return env.get_rank() == 0


def barrier_worker():
    from ..collective import barrier
    barrier()


def distributed_model(model):
    """Wrap a Layer for the active parallel mode
    (reference: fleet_base.py:883 — PipelineParallel / TensorParallel /
    ShardingParallel / DataParallel)."""
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        init()
        hcg = get_hybrid_communicate_group()
    mode = hcg.get_parallel_mode()
    from .. import meta_parallel
    if mode == "pipeline":
        from ..meta_parallel.spmd_pipeline import PipelineStageStack
        from ..meta_parallel.parallel_layers.pp_layers import PipelineLayer
        if not isinstance(model, PipelineLayer) and any(
                isinstance(sub, PipelineStageStack)
                for sub in model.sublayers(include_self=True)):
            # the model already carries an SPMD pipeline (stacked params
            # sharded over the pp mesh axis, scan+ppermute schedule): it IS
            # the distributed model — just lay its params on the mesh
            from ..spmd import apply_param_shardings
            return apply_param_shardings(model, hcg.mesh)
        return meta_parallel.PipelineParallel(model, hcg, _strategy())
    if mode == "model":
        return meta_parallel.TensorParallel(model, hcg, _strategy())
    if mode == "sharding":
        return meta_parallel.ShardingParallel(model, hcg, _strategy())
    from ..parallel import DataParallel
    return DataParallel(model)


def save_persistables(obj, dirname: str, asynchronous: bool = True):
    """Sharded async save of training state (reference: fleet_base.py:779
    save_persistables funnels every persistable through trainer 0; here
    each host writes only its own shards — distributed.checkpoint).

    ``obj`` is a TrainStep (full state incl. optimizer slots) or a Layer
    (params + buffers only)."""
    from .. import checkpoint as dckpt
    from ...jit.to_static import TrainStep
    if isinstance(obj, TrainStep):
        dckpt.save_train_step(obj, dirname, asynchronous=asynchronous)
        return
    state = {"params": {k: p._data for k, p in obj.named_parameters()},
             "buffers": {k: b._data for k, b in obj.named_buffers()}}
    dckpt.save(state, dirname, asynchronous=asynchronous)


def load_persistables(obj, dirname: str):
    """Restore state saved by save_persistables, resharding to the current
    mesh layout (reference: fleet_base.py load via executor)."""
    import jax

    from .. import checkpoint as dckpt
    from .. import env as dist_env
    from ...jit.to_static import TrainStep
    if isinstance(obj, TrainStep):
        dckpt.load_train_step(obj, dirname)
        return obj
    # Layer path: restore into the layer's current layout (mesh + specs)
    # and load through set_state_dict for shape validation + key reporting
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = dist_env.get_mesh()

    def sds(p, spec):
        if mesh is None:
            return jax.ShapeDtypeStruct(tuple(p.shape), p._data.dtype)
        return jax.ShapeDtypeStruct(
            tuple(p.shape), p._data.dtype,
            sharding=NamedSharding(mesh, spec or P()))

    target = {
        "params": {k: sds(p, getattr(p, "spec", None))
                   for k, p in obj.named_parameters()},
        "buffers": {k: sds(b, None) for k, b in obj.named_buffers()},
    }
    state = dckpt.load(dirname, target=target)
    params = dict(obj.named_parameters())
    bufs = dict(obj.named_buffers())
    obj.set_state_dict({**state.get("params", {}),
                        **state.get("buffers", {})})
    # set_state_dict re-asserts dtypes via jnp.asarray; re-pin shardings
    if mesh is not None:
        for k, v in state.get("params", {}).items():
            if k in params:
                params[k]._data = v
        for k, v in state.get("buffers", {}).items():
            if k in bufs:
                bufs[k]._data = v
    return obj


def reset():
    """Tear down fleet state (tests / re-init). The reference has no such
    API because its strategy is scoped to distributed_optimizer; ours is
    too (see below), but the topology/mesh globals still need a reset."""
    _fleet_state["initialized"] = False
    _fleet_state["strategy"] = None
    set_hybrid_communicate_group(None)
    env.reset()


def distributed_optimizer(optimizer, strategy: Optional[DistributedStrategy] = None):
    """reference: fleet_base.py:830 — the ONLY boundary where a
    DistributedStrategy changes training semantics. The meta-optimizer
    chain becomes a strategy SNAPSHOT attached to the returned optimizer:
    TrainStep reads gradient-merge / localsgd config exclusively from
    ``optimizer._fleet_strategy``, so a bare optimizer (never passed
    through here) is never rewired by a prior ``fleet.init`` — matching
    the reference, where an un-wrapped optimizer ignores the strategy.
    """
    snap = strategy if strategy is not None else _strategy()
    # snapshot (deep copy): later mutations of the user's strategy object
    # must not retroactively change an already-built optimizer
    frozen = DistributedStrategy()
    frozen.__dict__["_config"] = snap.to_dict()
    optimizer._fleet_strategy = frozen
    optimizer._hybrid_context = get_hybrid_communicate_group()
    return optimizer
