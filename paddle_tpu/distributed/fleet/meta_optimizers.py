"""LocalSGD / AdaptiveLocalSGD training step.

reference parity: fleet/meta_optimizers/localsgd_optimizer.py
(LocalSGDOptimizer:30 — k local steps between parameter broadcasts;
AdaptiveLocalSGDOptimizer:443 — k adapted from the loss ratio, the
AdaComm schedule k_t = ceil(k_0 * sqrt(F(w_t)/F(w_0)))).

TPU-native redesign: the reference mutates the Program to skip grad
allreduces and injects broadcast ops. Here each dp replica owns a
DISTINCT parameter copy — a leading replica axis sharded over ``dp`` —
and the whole local step runs inside ``shard_map`` where no cross-replica
collective exists at all; the sync step is one ``pmean`` over the dp axis
every k steps. XLA compiles both as single donated programs; between
syncs the only ICI traffic is zero.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

__all__ = ["LocalSGDTrainStep"]


class LocalSGDTrainStep:
    """Compile (model, loss, optimizer) into a LocalSGD step over the
    ``axis`` mesh dimension.

    Every call runs ONE local step on each replica's own parameters (the
    batch is split over ``axis``); every ``k_steps``-th call additionally
    averages parameters across replicas. ``adaptive=True`` re-derives k
    from the loss ratio at every sync (AdaComm; reference
    localsgd_optimizer.py:443).

    Restriction: parameters must be replicated modulo the replica axis —
    LocalSGD composes with dp/sharding data parallelism, not with tensor
    parallelism inside the same step (matching the reference, whose
    LocalSGD meta-optimizer is dp-only).
    """

    def __init__(self, layer, loss_fn: Callable, optimizer, mesh,
                 k_steps: int = 1, axis: str = "dp",
                 adaptive: bool = False, min_k_steps: int = 1,
                 max_k_steps: int = 16):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ...jit.functional import (bind, buffer_arrays,
                                       trainable_param_arrays)
        from ...core.random import make_rng, trace_rng
        from ...core.tensor import Tensor, no_grad

        if axis not in mesh.axis_names:
            raise ValueError(f"mesh has no axis {axis!r}: {mesh.axis_names}")
        self.layer = layer
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self.axis = axis
        self.k_steps = max(1, int(k_steps))
        self.adaptive = adaptive
        self.min_k = max(1, int(min_k_steps))
        self.max_k = int(max_k_steps)
        self._k0 = self.k_steps
        self._loss0: Optional[float] = None
        self.step_count = 0
        self._stats = {"localsgd_syncs": 0, "local_steps": 0}
        self._make_rng = make_rng
        D = mesh.shape[axis]
        self.num_replicas = D

        params0 = trainable_param_arrays(layer)
        self.buffers = buffer_arrays(layer)

        def rep(a):
            # per-replica copy: leading replica dim, sharded over `axis`
            return jax.device_put(
                jnp.broadcast_to(a, (D,) + a.shape),
                NamedSharding(mesh, P(axis, *([None] * a.ndim))))

        self.params = {k: rep(v) for k, v in params0.items()}
        slots0 = optimizer.init_state(params0)
        self.opt_state = jax.tree_util.tree_map(
            lambda a: rep(a) if hasattr(a, "shape") and a.ndim > 0 else a,
            slots0)

        # ---- compiled programs -------------------------------------------
        opt = optimizer

        def local_fn(p_rep, bufs, opt_rep, lr, t, key, batch_rep):
            """Runs INSIDE shard_map: leading replica dim of size 1."""
            p = {k: v[0] for k, v in p_rep.items()}
            st = jax.tree_util.tree_map(
                lambda a: a[0] if hasattr(a, "ndim") and a.ndim > 0 else a,
                opt_rep)
            batch = [b[0] for b in batch_rep]
            key = jax.random.fold_in(key, jax.lax.axis_index(axis))

            def compute_loss(pp):
                tensors = [Tensor(b) for b in batch]
                new_bufs = dict(bufs)
                with trace_rng(key), no_grad():
                    with bind(layer, pp, new_bufs):
                        loss = loss_fn(layer, *tensors)
                arr = loss._data if isinstance(loss, Tensor) else loss
                return arr.astype(jnp.float32), new_bufs

            (loss, new_bufs), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(p)
            new_p, new_st = opt.apply_gradients(p, grads, st, lr, t)
            new_p_rep = {k: v[None] for k, v in new_p.items()}
            new_st_rep = jax.tree_util.tree_map(
                lambda a: a[None] if hasattr(a, "ndim") else a, new_st)
            # buffer updates (BN/IN running stats) are averaged across
            # replicas every step — the per-replica batches differ, so the
            # mean is the stats over the union batch (SyncBN-flavoured;
            # the reference's LocalSGD leaves BN stats per-replica and
            # broadcasts rank 0's at the end, which silently discards
            # k-1/k of the statistics)
            new_bufs = {
                k: jax.lax.pmean(v, axis)
                if jnp.issubdtype(v.dtype, jnp.floating) else v
                for k, v in new_bufs.items()}
            # mean replica loss for reporting
            loss = jax.lax.pmean(loss, axis)
            return new_p_rep, new_st_rep, new_bufs, loss[None]

        pspec = {k: P(axis, *([None] * v.ndim))
                 for k, v in params0.items()}
        stspec = jax.tree_util.tree_map(
            lambda a: P(axis, *([None] * getattr(a, "ndim", 0)))
            if hasattr(a, "shape") and a.ndim > 0 else P(), slots0)
        from jax.sharding import PartitionSpec as _P

        def batch_specs(batch):
            return [ _P(axis, *([None] * (b.ndim - 1))) for b in batch ]

        self._local_cache: Dict = {}

        def make_local(bspecs):
            in_specs = (pspec, _P(), stspec, _P(), _P(), _P(),
                        list(bspecs))
            out_specs = (pspec, stspec, _P(), _P(axis))
            try:
                sm = jax.shard_map(local_fn, mesh=mesh, in_specs=in_specs,
                                   out_specs=out_specs, check_vma=False)
            except (AttributeError, TypeError):   # older jax
                from jax.experimental.shard_map import shard_map
                sm = shard_map(local_fn, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, check_rep=False)
            return jax.jit(sm, donate_argnums=(0, 2))

        self._make_local = make_local

        def sync_fn(p_rep):
            # parameter average over replicas = mean over the leading dim
            return {k: jnp.broadcast_to(jnp.mean(v, axis=0,
                                                 keepdims=True),
                                        v.shape).astype(v.dtype)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v
                    for k, v in p_rep.items()}

        self._sync = jax.jit(sync_fn, donate_argnums=(0,))

    def __call__(self, *batch):
        from ...core.tensor import Tensor
        raw = [b._data if isinstance(b, Tensor) else jnp.asarray(b)
               for b in batch]
        rep = []
        for b in raw:
            if b.shape[0] % self.num_replicas:
                raise ValueError(
                    f"batch dim {b.shape[0]} not divisible by "
                    f"{self.num_replicas} replicas")
            rep.append(b.reshape((self.num_replicas,
                                  b.shape[0] // self.num_replicas)
                                 + b.shape[1:]))
        from jax.sharding import PartitionSpec as P
        bspecs = tuple(P(self.axis, *([None] * (b.ndim - 1)))
                       for b in rep)
        jitted = self._local_cache.get(
            (bspecs, tuple((b.shape, str(b.dtype)) for b in rep)))
        if jitted is None:
            jitted = self._make_local(bspecs)
            self._local_cache[(bspecs, tuple((b.shape, str(b.dtype))
                                             for b in rep))] = jitted
        self.step_count += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        t = jnp.asarray(self.step_count, jnp.int32)
        key = self._make_rng("localsgd")
        self.params, self.opt_state, self.buffers, loss = jitted(
            self.params, self.buffers, self.opt_state, lr, t, key, rep)
        # host-sync the loss ONLY when the AdaComm schedule needs it — a
        # per-step float() would serialize dispatch between local steps
        if self.adaptive and self._loss0 is None:
            self._loss0 = max(float(loss[0]), 1e-12)
        self._stats["local_steps"] += 1
        if self.step_count % self.k_steps == 0:
            # LocalSGD SYNC boundary: replicas average parameters here —
            # surfaced to the monitor registry so the k-step cadence (and
            # AdaComm's adaptation of it) is observable next to the step
            # timings (docs/OBSERVABILITY.md)
            self._stats["localsgd_syncs"] += 1
            from ...core.flags import get_flag
            if get_flag("monitor"):
                from ...monitor import get_registry
                reg = get_registry()
                reg.counter("localsgd_syncs_total",
                            "LocalSGD parameter-averaging boundaries"
                            ).inc(axis=self.axis)
                reg.gauge("localsgd_k_steps",
                          "current LocalSGD sync period (AdaComm adapts "
                          "this)").set(self.k_steps, axis=self.axis)
            self.params = self._sync(self.params)
            if self.adaptive:
                # AdaComm: k_t = ceil(k_0 * sqrt(F(w_t) / F(w_0)))
                import math
                loss_val = float(loss[0])
                k = math.ceil(self._k0
                              * math.sqrt(max(loss_val, 1e-12)
                                          / self._loss0))
                self.k_steps = min(max(k, self.min_k), self.max_k)
        return Tensor(loss[0])

    def stats(self) -> dict:
        """Telemetry snapshot (TrainStep.stats() analogue): local steps,
        parameter-averaging sync boundaries, and the current/initial k."""
        d = dict(self._stats)
        d.update(steps=self.step_count, k_steps=self.k_steps,
                 initial_k_steps=self._k0, num_replicas=self.num_replicas)
        return d

    def sync_to_layer(self):
        """Average replicas and write back into the Layer."""
        synced = self._sync(self.params)
        self.params = synced
        for k, p in self.layer.named_parameters():
            if k in synced:
                p._data = synced[k][0]
