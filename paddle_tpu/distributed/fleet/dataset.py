"""Industrial file datasets: InMemoryDataset / QueueDataset.

reference parity: python/paddle/distributed/fleet/dataset/dataset.py —
DatasetBase(:39 init: batch_size/thread_num/pipe_command/use_var),
set_filelist(:124), InMemoryDataset(load_into_memory:787,
local_shuffle:899, global_shuffle:931, release_memory:991,
get_memory_data_size:1030) over the C++ MultiSlotDataFeed
(fluid/framework/data_feed.cc).

TPU-native redesign: the C++ data-feed pipeline (pipe_command subprocess
per file, slot parsing) is reproduced host-side: each file is streamed
through the user's `pipe_command` (a real shell pipeline, like the
reference) or read directly, parsed line-by-line by `parse_fn` (default:
the reference's MultiSlot text format `slot_size v v ... slot_size ...`),
and batched into fixed-shape numpy arrays ready for a jitted step.
`global_shuffle` shards samples across trainers by hash, matching the
reference's cross-trainer exchange semantics on a single host.
"""

from __future__ import annotations

import random
import subprocess
from typing import Callable, List, Sequence

import numpy as np

__all__ = ["DatasetBase", "InMemoryDataset", "QueueDataset"]


def _stable_mix(seed: int, i: int) -> int:
    """Interpreter-independent sample->trainer hash (python's builtin
    hash() is implementation-defined, so trainers on different runtimes
    could partition inconsistently)."""
    x = (seed * 0x9E3779B97F4A7C15 + i * 0xBF58476D1CE4E5B9) \
        & 0xFFFFFFFFFFFFFFFF
    x ^= x >> 31
    x = (x * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 29)


def _parse_multislot(line: str):
    """The reference MultiSlotDataFeed text format: for each slot,
    `<n> v1 ... vn` (floats); returns a list of np arrays, one per slot."""
    parts = line.split()
    out = []
    i = 0
    while i < len(parts):
        n = int(parts[i])
        vals = parts[i + 1:i + 1 + n]
        if len(vals) != n:
            raise ValueError(
                f"corrupt MultiSlot line: slot declares {n} values but "
                f"{len(vals)} remain: {line[:120]!r}")
        out.append(np.asarray([float(v) for v in vals], np.float32))
        i += 1 + n
    return out


class DatasetBase:
    """reference: dataset.py DatasetBase:39."""

    def __init__(self):
        self.batch_size = 1
        self.thread_num = 1
        self.pipe_command = None
        self.use_var: Sequence = ()
        self.filelist: List[str] = []
        self.parse_fn: Callable = _parse_multislot
        self.drop_last = False

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command=None, input_type=0, fs_name="", fs_ugi="",
             download_cmd="cat", parse_fn=None, drop_last=False,
             shared_filelist=False, **kwargs):
        # shared_filelist=True declares that EVERY trainer loads the same
        # files, which is what makes the hash partition in global_shuffle
        # a correct exchange substitute
        self.batch_size = batch_size
        self.thread_num = thread_num
        self.use_var = use_var or ()
        self.pipe_command = pipe_command
        if parse_fn is not None:
            self.parse_fn = parse_fn
        self.drop_last = drop_last
        self.shared_filelist = shared_filelist

    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    def _read_file(self, path: str):
        if self.pipe_command:
            stdin_f = open(path, "rb")
            proc = subprocess.Popen(self.pipe_command, shell=True,
                                    stdin=stdin_f,
                                    stdout=subprocess.PIPE, text=True)
            drained = False
            try:
                for line in proc.stdout:
                    line = line.strip()
                    if line:
                        yield self.parse_fn(line)
                drained = True
            finally:
                proc.stdout.close()
                if drained:
                    # only a fully-drained pipe reports failures; an early
                    # consumer break (generator close) just kills the child
                    if proc.wait() != 0:
                        raise RuntimeError(
                            f"pipe_command {self.pipe_command!r} failed "
                            f"on {path}")
                else:
                    proc.kill()
                    proc.wait()
                stdin_f.close()
        else:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield self.parse_fn(line)

    def _iter_samples(self):
        for path in self.filelist:
            yield from self._read_file(path)

    @staticmethod
    def _collate(buf):
        n_slots = len(buf[0])
        return [np.stack([s[i] for s in buf]) for i in range(n_slots)]

    def _batches(self, samples):
        buf = []
        for s in samples:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield self._collate(buf)
                buf = []
        if buf and not self.drop_last:
            yield self._collate(buf)


class QueueDataset(DatasetBase):
    """Streaming dataset: files are parsed as iteration proceeds
    (reference: dataset.py QueueDataset:1221 over the C++ queue feed)."""

    def __iter__(self):
        return self._batches(self._iter_samples())


class InMemoryDataset(DatasetBase):
    """Load-then-train dataset with shuffles (reference:
    dataset.py InMemoryDataset:496)."""

    def __init__(self):
        super().__init__()
        self._memory: List = []
        self._seed = 0

    def load_into_memory(self, is_shuffle=False):
        self._memory = list(self._iter_samples())
        if is_shuffle:
            self.local_shuffle()

    def preload_into_memory(self, thread_num=None):
        self.load_into_memory()

    def wait_preload_done(self):
        pass

    def local_shuffle(self):
        rng = random.Random(self._seed)
        self._seed += 1
        rng.shuffle(self._memory)

    def global_shuffle(self, fleet=None, thread_num=12):
        """Cross-trainer exchange: keep the samples this trainer owns by
        hash (reference exchanges via gloo; single-host keeps the same
        ownership contract)."""
        from ..env import get_rank, get_world_size
        n = get_world_size()
        me = get_rank()
        if n > 1:
            if not getattr(self, "shared_filelist", False):
                raise RuntimeError(
                    "global_shuffle with world_size > 1 requires "
                    "init(shared_filelist=True) and the SAME full "
                    "filelist on every trainer (each keeps its hash "
                    "shard). With per-trainer split filelists the data "
                    "is already partitioned — use local_shuffle().")
            self._memory = [s for i, s in enumerate(self._memory)
                            if _stable_mix(self._seed, i) % n == me]
        self.local_shuffle()

    def release_memory(self):
        self._memory = []

    def get_memory_data_size(self, fleet=None) -> int:
        return len(self._memory)

    def get_shuffle_data_size(self, fleet=None) -> int:
        return len(self._memory)

    def __iter__(self):
        return self._batches(iter(self._memory))
