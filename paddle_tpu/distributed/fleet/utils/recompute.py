"""Fleet utilities: activation recomputation.

reference parity: python/paddle/distributed/fleet/utils/recompute.py
(RecomputeFunction.forward/backward:63,182 — CUDA RNG-state stashing +
re-forward under enable_grad). The TPU-native redesign is `jax.checkpoint`:
under jit the XLA backward rematerializes the segment instead of saving
activations; in eager the tape's VJP closure holds only the segment inputs.
RNG consistency is free here — dropout keys are split at Python trace time
(core/random.trace_rng), so the rematerialized forward replays the same
keys without the reference's fork_rng dance.
"""

from __future__ import annotations

from typing import Any

import jax

from ....core.tensor import Tensor, apply
from ....nn.layer import Layer

__all__ = ["recompute", "recompute_sequential", "resolve_checkpoint_policy"]

#: named selective-remat policies (jax.checkpoint_policies). The TPU
#: default for transformer stacks is ``dots_with_no_batch_dims_saveable``:
#: keep MXU (matmul) outputs resident, rematerialize only the cheap
#: elementwise tail — far less recompute FLOPs than full remat for a
#: modest HBM cost (the T5X/MaxText recipe).
_POLICY_NAMES = (
    # NOTE: only plain PREDICATES belong here. jax.checkpoint_policies
    # also exports factories (offload_dot_with_no_batch_dims,
    # save_only_these_names, ...) that take configuration and RETURN a
    # predicate — pass the constructed predicate as a callable instead.
    "dots_saveable",
    "dots_with_no_batch_dims_saveable",
    "checkpoint_dots",
    "checkpoint_dots_with_no_batch_dims",
    "everything_saveable",
    "nothing_saveable",
)
_POLICY_ALIASES = {
    "save_dots": "dots_saveable",
    "save_dots_no_batch": "dots_with_no_batch_dims_saveable",
    "full": "nothing_saveable",
    "none": "everything_saveable",
}


def resolve_checkpoint_policy(policy):
    """Resolve a remat policy spec to a ``jax.checkpoint_policies`` predicate.

    Accepts None (full remat — jax.checkpoint's default), a callable
    (returned as-is), or a policy name / alias string. Model configs carry
    the string form (``recompute_policy='dots_with_no_batch_dims_saveable'``)
    so configs stay picklable/serializable."""
    if policy is None or callable(policy):
        return policy
    name = _POLICY_ALIASES.get(str(policy), str(policy))
    if name not in _POLICY_NAMES:
        raise ValueError(
            f"unknown recompute policy {policy!r}; expected one of "
            f"{sorted(_POLICY_NAMES + tuple(_POLICY_ALIASES))} or a "
            "jax.checkpoint_policies callable")
    return getattr(jax.checkpoint_policies, name)


def recompute(function, *args, use_reentrant: bool = True,
              preserve_rng_state: bool = True, policy=None, **kwargs):
    """Run ``function(*args)`` with activation checkpointing.

    ``function`` may be a Layer (its parameters join the gradient path) or
    any callable over Tensors. Memory: the backward keeps only the segment
    inputs + params and rematerializes intermediates (reference:
    fleet/utils/recompute.py:63; here via jax.checkpoint, which also
    applies inside a jitted TrainStep trace).

    ``policy`` (TPU-native extension): a ``jax.checkpoint_policies``
    predicate for SELECTIVE checkpointing — e.g.
    ``dots_with_no_batch_dims_saveable`` keeps matmul outputs resident and
    rematerializes only the cheap elementwise tail, a far better
    FLOPs/HBM trade than full recompute on TPU.
    """
    del use_reentrant, preserve_rng_state   # parity knobs; single behavior
    policy = resolve_checkpoint_policy(policy)

    # Gradients only flow through explicit apply() args, so parameters must
    # be passed in — harvest them from the callable: the Layer itself, a
    # bound method's Layer, and any Layer/Tensor captured in closure cells
    # (the `recompute(lambda x: f(block(x)), x)` pattern).
    layers = []
    if isinstance(function, Layer):
        layers.append(function)
    self_obj = getattr(function, "__self__", None)
    if isinstance(self_obj, Layer) and self_obj not in layers:
        layers.append(self_obj)
    loose_tensors = []
    for cell in getattr(function, "__closure__", None) or ():
        try:
            v = cell.cell_contents
        except ValueError:
            continue
        if isinstance(v, Layer) and v not in layers:
            layers.append(v)
        elif isinstance(v, Tensor) and not v.stop_gradient:
            loose_tensors.append(v)

    p_entries = []                       # (layer_idx, name, tensor)
    for li, lyr in enumerate(layers):
        for k, p in lyr.named_parameters():
            p_entries.append((li, k, p))
    p_tensors = [p for _, _, p in p_entries]
    n_p = len(p_tensors)
    n_loose = len(loose_tensors)

    tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    tensor_args = [args[i] for i in tensor_idx]

    def pure(*raw):
        import contextlib
        from ....jit.functional import bind
        per_layer = [dict() for _ in layers]
        for (li, k, _), arr in zip(p_entries, raw[:n_p]):
            per_layer[li][k] = arr
        xs = list(args)
        for i, arr in zip(tensor_idx, raw[n_p + n_loose:]):
            xs[i] = Tensor(arr)
        with contextlib.ExitStack() as stack:
            for t, arr in zip(loose_tensors, raw[n_p:n_p + n_loose]):
                saved = t._data
                t._data = arr
                stack.callback(lambda t=t, s=saved: setattr(t, "_data", s))
            for lyr, p_arrays in zip(layers, per_layer):
                stack.enter_context(bind(lyr, p_arrays, None))
            out = (layers[0](*xs, **kwargs) if isinstance(function, Layer)
                   else function(*xs, **kwargs))
        outs = out if isinstance(out, (tuple, list)) else (out,)
        flat = tuple(o._data if isinstance(o, Tensor) else o for o in outs)
        return flat if len(flat) > 1 else flat[0]

    ck = jax.checkpoint(pure, policy=policy)
    return apply(ck, *p_tensors, *loose_tensors, *tensor_args,
                 name="recompute")


class _Segment(Layer):
    """A chunk of layers/callables as ONE Layer, so recompute() harvests
    the chunk's parameters into the gradient path."""

    def __init__(self, fns):
        super().__init__()
        self._fns = list(fns)
        for i, f in enumerate(self._fns):
            if isinstance(f, Layer):
                self.add_sublayer(str(i), f)

    def forward(self, *xs):
        cur = xs
        for f in self._fns:
            cur = f(*cur) if isinstance(cur, tuple) else f(cur)
        return cur


def recompute_sequential(ctx: Any, functions, *args, **kwargs):
    """Checkpoint a sequence of layers segment by segment (reference:
    fleet/utils/recompute.py recompute_sequential — segments kwarg)."""
    segments = int((ctx or {}).get("segments", 1)) if isinstance(ctx, dict) \
        else int(getattr(ctx, "segments", 1) or 1)
    funcs = list(functions)
    if not funcs:
        return args[0] if len(args) == 1 else args
    seg_size = max(1, (len(funcs) + segments - 1) // segments)
    out = args
    for s in range(0, len(funcs), seg_size):
        seg = _Segment(funcs[s:s + seg_size])
        out = recompute(seg, *(out if isinstance(out, tuple) else (out,)),
                        **kwargs)
    return out
