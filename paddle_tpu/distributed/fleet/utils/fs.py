"""Filesystem clients for fleet checkpoint transport.

reference parity: python/paddle/distributed/fleet/utils/fs.py —
FS base(:57), LocalFS(:119), HDFSClient(:423, shelling out to the
hadoop CLI). The checkpoint/elastic stack moves state through this
interface so remote stores slot in without touching training code.
"""

from __future__ import annotations

import logging
import os
import random
import shutil
import subprocess
import time
from typing import Callable, List, Optional, Tuple, TypeVar

__all__ = ["ExecuteError", "FSFileExistsError", "FSFileNotExistsError",
           "FSTimeOut", "FSShellCmdAborted", "FS", "LocalFS", "HDFSClient",
           "retry_with_backoff"]

logger = logging.getLogger("paddle_tpu.fs")

_T = TypeVar("_T")


def retry_with_backoff(fn: Callable[[], _T], retries: int = 3,
                       base_delay: float = 0.5, max_delay: float = 30.0,
                       jitter: float = 0.5,
                       retry_on: Tuple[type, ...] = (Exception,),
                       what: str = "", sleep=time.sleep) -> _T:
    """Run ``fn`` with exponential backoff + jitter on transient failure.

    Replaces the reference's fixed-interval ``sleep_inter`` retry loop
    (fs.py HDFSClient): fixed-interval retries against a struggling
    store synchronize every worker's retries into the very thundering
    herd that is keeping the store struggling. Delay for attempt k is
    ``min(max_delay, base_delay * 2**k) * (1 + jitter*U[0,1))``; each
    failed attempt logs one line (store operations are sparse — silence
    here costs hours of debugging later). Exceptions carrying
    ``retryable = False`` (permanent failures: missing CLI, bad
    arguments) re-raise immediately; so do exception types outside
    ``retry_on``. Used by the HDFS transport and the ElasticManager
    heartbeat/marker writes."""
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            if not getattr(e, "retryable", True) or attempt >= retries:
                raise
            delay = min(max_delay, base_delay * (2.0 ** attempt))
            delay *= 1.0 + jitter * random.random()
            attempt += 1
            logger.warning(
                "%s failed (attempt %d/%d): %r — retrying in %.2fs",
                what or getattr(fn, "__name__", "operation"), attempt,
                retries + 1, e, delay)
            sleep(delay)


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FSTimeOut(Exception):
    pass


class FSShellCmdAborted(ExecuteError):
    pass


class _ProbeFalse(Exception):
    """Internal: a hadoop -test probe returned 'condition false'."""


class FS:
    """Abstract transport (reference: fs.py FS:57)."""

    def ls_dir(self, fs_path) -> Tuple[List[str], List[str]]:
        raise NotImplementedError

    def is_file(self, fs_path) -> bool:
        raise NotImplementedError

    def is_dir(self, fs_path) -> bool:
        raise NotImplementedError

    def is_exist(self, fs_path) -> bool:
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self) -> bool:
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=False):
        raise NotImplementedError

    def list_dirs(self, fs_path) -> List[str]:
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError

    def cat(self, fs_path=None) -> str:
        raise NotImplementedError


class LocalFS(FS):
    """Local filesystem (reference: fs.py LocalFS:119)."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for e in os.listdir(fs_path):
            (dirs if os.path.isdir(os.path.join(fs_path, e))
             else files).append(e)
        return dirs, files

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        if self.is_dir(fs_path):
            shutil.rmtree(fs_path)
        elif self.is_file(fs_path):
            os.remove(fs_path)

    def need_upload_download(self):
        return False

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if test_exists and not self.is_exist(src_path):
            raise FSFileNotExistsError(f"{src_path} does not exist")
        if self.is_exist(dst_path):
            if not overwrite:
                raise FSFileExistsError(f"{dst_path} exists")
            # REPLACE the destination: shutil.move into an existing dir
            # would nest the source inside it
            self.delete(dst_path)
        shutil.move(src_path, dst_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(f"{fs_path} exists")
        with open(fs_path, "a"):
            pass

    def upload(self, local_path, fs_path):
        # COPY (like the remote transports): the caller keeps its local
        # source — upload must never destroy the only local checkpoint
        if os.path.isdir(local_path):
            shutil.copytree(local_path, fs_path, dirs_exist_ok=True)
        else:
            shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)

    def cat(self, fs_path=None):
        with open(fs_path) as f:
            return f.read()


class HDFSClient(FS):
    """HDFS transport shelling out to the hadoop CLI (reference:
    fs.py HDFSClient:423 — same `hadoop fs -ls/-put/-get` command
    surface). Raises ExecuteError with the command output when the CLI
    is absent or a command fails."""

    def __init__(self, hadoop_home: str, configs: Optional[dict] = None,
                 time_out: int = 5 * 60 * 1000, sleep_inter: int = 1000,
                 retries: int = 3):
        self._hadoop = os.path.join(hadoop_home, "bin", "hadoop") \
            if hadoop_home else "hadoop"
        self._configs = configs or {}
        self._timeout = time_out / 1000.0
        # sleep_inter (ms, reference parity) seeds the BASE delay of the
        # backoff schedule; the fixed-interval loop it named is gone
        self._base_delay = max(sleep_inter, 1) / 1000.0
        self._retries = max(0, int(retries))

    def _run(self, *args, probe: bool = False,
             idempotent: bool = True) -> str:
        """Run a hadoop fs command with retry/backoff. `probe=True` is
        the `-test` mode: return code 1 with empty stderr means
        "condition false" (not an error) and raises _ProbeFalse; every
        other failure — missing CLI, permissions, network — still
        raises, so a broken transport can NEVER masquerade as "file does
        not exist". Transient failures (nonzero exit, CLI timeout) are
        retried with exponential backoff; a missing CLI is permanent and
        raises immediately. ``idempotent=False`` (mv/put/touchz)
        disables retry entirely: a timed-out rename may have SUCCEEDED
        server-side, and re-running it would convert that success into a
        spurious "source does not exist" failure — those callers must
        see the first error and decide with a probe."""
        cmd = [self._hadoop, "fs"]
        for k, v in self._configs.items():
            cmd += [f"-D{k}={v}"]
        cmd += list(args)

        def attempt() -> str:
            try:
                out = subprocess.run(cmd, capture_output=True, text=True,
                                     timeout=self._timeout)
            except FileNotFoundError as e:
                err = ExecuteError(
                    f"hadoop CLI not found at {self._hadoop!r}: {e}")
                err.retryable = False      # retrying won't grow a CLI
                raise err from e
            except subprocess.TimeoutExpired as e:
                raise FSTimeOut(f"{' '.join(cmd)} timed out") from e
            if out.returncode != 0:
                if probe and out.returncode == 1 and not out.stderr.strip():
                    raise _ProbeFalse()
                raise ExecuteError(
                    f"{' '.join(cmd)} failed: {out.stderr.strip()[:500]}")
            return out.stdout

        return retry_with_backoff(
            attempt,
            retries=self._retries if idempotent else 0,
            base_delay=self._base_delay,
            retry_on=(ExecuteError, FSTimeOut),
            what=" ".join(cmd[:4]) + (" ..." if len(cmd) > 4 else ""))

    def ls_dir(self, fs_path):
        out = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in out.splitlines():
            # -ls format: perms repl owner group size date time path — the
            # path (which may contain spaces) is everything after field 7
            parts = line.split(None, 7)
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[7])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]

    def is_exist(self, fs_path):
        try:
            self._run("-test", "-e", fs_path, probe=True)
            return True
        except _ProbeFalse:
            return False

    def is_file(self, fs_path):
        try:
            self._run("-test", "-f", fs_path, probe=True)
            return True
        except _ProbeFalse:
            return False

    def is_dir(self, fs_path):
        try:
            self._run("-test", "-d", fs_path, probe=True)
            return True
        except _ProbeFalse:
            return False

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path, idempotent=False)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        self._run("-rm", "-r", "-f", fs_path)

    def need_upload_download(self):
        return True

    def rename(self, fs_src_path, fs_dst_path):
        self._run("-mv", fs_src_path, fs_dst_path, idempotent=False)

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=False):
        if test_exists and not self.is_exist(fs_src_path):
            raise FSFileNotExistsError(f"{fs_src_path} does not exist")
        if self.is_exist(fs_dst_path):
            if not overwrite:
                raise FSFileExistsError(f"{fs_dst_path} exists")
            self.delete(fs_dst_path)
        self._run("-mv", fs_src_path, fs_dst_path, idempotent=False)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(f"{fs_path} exists")
        self._run("-touchz", fs_path, idempotent=False)

    def cat(self, fs_path=None):
        return self._run("-cat", fs_path)
