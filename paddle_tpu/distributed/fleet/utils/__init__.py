"""fleet.utils: recompute + filesystem transports (reference:
python/paddle/distributed/fleet/utils/)."""

from .recompute import *  # noqa: F401,F403
from . import fs  # noqa: F401
from .fs import HDFSClient, LocalFS  # noqa: F401
