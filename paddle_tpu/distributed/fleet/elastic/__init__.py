"""Elastic training: failure detection + restartable training loop.

reference parity: fleet/elastic/manager.py:103-354 — ElasticManager watches
etcd for host membership, decides HOLD/RESTART/COMPLETED/ERROR, kills and
relaunches local trainers between min/max parallelism; env protocol
PADDLE_ELASTIC_* (np range, fault tolerance level).

TPU-native redesign: etcd is replaced by a file-based heartbeat registry
(one small file per worker under a shared dir — on TPU pods typically NFS
or the pod's shared filesystem; no external KV service is assumed), and
the "kill+relaunch" model is the supervisor in `run_elastic`, which pairs
with TrainStep.save/load (bit-exact resume, jit/to_static.py) so a restart
resumes from the last good step instead of step 0. In-training device
failure surfaces as an exception on the single controller — the restart
model matches the reference's (no in-flight NCCL repair there either).
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Optional

__all__ = ["ElasticStatus", "ElasticManager", "run_elastic"]


class ElasticStatus:
    COMPLETED = "completed"
    RESTART = "restart"
    HOLD = "hold"
    ERROR = "error"
    EXIT = "exit"


class ElasticManager:
    """Worker membership via heartbeat files (reference: etcd hosts path).

    Each worker touches ``<root>/worker_<rank>.hb`` with its pid and
    timestamp; `watch()` classifies the cluster state: all expected workers
    alive -> HOLD, a worker stale/dead but replaceable within
    [min_np, max_np] -> RESTART, job marker complete -> COMPLETED.
    """

    def __init__(self, root: Optional[str] = None,
                 rank: Optional[int] = None, np_: Optional[int] = None,
                 min_np: Optional[int] = None, max_np: Optional[int] = None,
                 timeout: float = 30.0, job_id: Optional[str] = None):
        env = os.environ
        base = root or env.get("PADDLE_ELASTIC_DIR",
                               "/tmp/paddle_tpu_elastic")
        # per-job namespace: a finished job's COMPLETED marker must not
        # classify the next job (reference: etcd prefix = job_id)
        job = job_id or env.get("PADDLE_ELASTIC_JOB_ID")
        self.root = os.path.join(base, job) if job else base
        self.rank = int(rank if rank is not None
                        else env.get("PADDLE_TRAINER_ID", 0))
        self.np = int(np_ if np_ is not None
                      else env.get("PADDLE_TRAINERS_NUM", 1))
        elastic = env.get("PADDLE_ELASTIC_NP", "")
        if ":" in elastic:
            lo, hi = elastic.split(":")
            self.min_np, self.max_np = int(lo), int(hi)
        else:
            self.min_np = int(min_np if min_np is not None else self.np)
            self.max_np = int(max_np if max_np is not None else self.np)
        self.timeout = timeout
        os.makedirs(self.root, exist_ok=True)
        self.enabled = self.max_np > 1 or "PADDLE_ELASTIC_NP" in env

    # -- heartbeat ---------------------------------------------------------
    def _hb_path(self, rank):
        return os.path.join(self.root, f"worker_{rank}.hb")

    def _write_marker(self, path: str, payload: str):
        """One registry-store write. On TPU pods the registry dir is
        NFS/shared-fs: transient EIO/ESTALE under load is normal, so all
        store writes go through exponential backoff with jitter (the
        same helper the HDFS transport uses) — a worker must not be
        declared dead because one heartbeat write hit a slow NFS
        server. Write-then-rename keeps readers from seeing a torn
        heartbeat as a dead worker."""
        from ..utils.fs import retry_with_backoff

        def attempt():
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                f.write(payload)
            os.replace(tmp, path)

        retry_with_backoff(attempt, retries=3, base_delay=0.05,
                           max_delay=2.0, retry_on=(OSError,),
                           what=f"elastic store write {path}")

    def beat(self):
        self._write_marker(self._hb_path(self.rank), json.dumps(
            {"pid": os.getpid(), "ts": time.time()}))

    def alive_workers(self):
        now = time.time()
        alive = []
        for name in os.listdir(self.root):
            if not name.endswith(".hb"):
                continue
            try:
                with open(os.path.join(self.root, name)) as f:
                    hb = json.load(f)
                if now - hb["ts"] <= self.timeout:
                    alive.append(int(name[len("worker_"):-3]))
            except (ValueError, OSError):
                continue
        return sorted(alive)

    def mark_completed(self):
        self._write_marker(os.path.join(self.root, "COMPLETED"),
                           str(time.time()))

    # -- state machine (reference: manager.py:324 watch) -------------------
    def watch(self) -> str:
        if os.path.exists(os.path.join(self.root, "COMPLETED")):
            return ElasticStatus.COMPLETED
        alive = self.alive_workers()
        if len(alive) >= self.np:
            return ElasticStatus.HOLD
        if len(alive) >= self.min_np:
            return ElasticStatus.RESTART     # degraded but viable: rescale
        return ElasticStatus.ERROR


def run_elastic(train_fn: Callable[[Optional[str]], None],
                checkpoint_path: str, max_restarts: int = 3,
                manager: Optional[ElasticManager] = None):
    """Supervised restartable training (the reference's relaunch loop,
    manager.py LauncherInterface, folded into-process for the SPMD
    single-controller model).

    ``train_fn(resume_path_or_None)`` runs the training loop, calling
    TrainStep.save(checkpoint_path) at intervals; on exception the
    supervisor retries from the latest checkpoint up to max_restarts.
    A background thread beats the heartbeat every timeout/3 so peers'
    watch() sees this worker alive for the whole run.
    """
    import threading

    mgr = manager or ElasticManager()
    # stale COMPLETED from a previous job under the same root must not
    # instantly "finish" this one
    marker = os.path.join(mgr.root, "COMPLETED")
    if os.path.exists(marker):
        try:
            os.remove(marker)
        except OSError:
            pass

    stop = threading.Event()

    def heartbeat_loop():
        while not stop.is_set():
            try:
                mgr.beat()
            except OSError:
                pass
            stop.wait(max(mgr.timeout / 3.0, 0.1))

    hb = threading.Thread(target=heartbeat_loop, daemon=True)
    hb.start()
    restarts = 0
    try:
        while True:
            resume = (checkpoint_path if os.path.exists(checkpoint_path)
                      else None)
            try:
                result = train_fn(resume)
                mgr.mark_completed()
                return result
            except KeyboardInterrupt:
                raise
            except Exception:
                restarts += 1
                if restarts > max_restarts:
                    raise
                time.sleep(min(2.0 ** restarts, 30.0))
    finally:
        stop.set()
