"""Distributed (sharded, async, reshardable) checkpointing.

reference parity: fleet.save_persistables / fleet_base.py:779 (per-variable
persistable save through the executor), operators/save_op.cc /
load_op.cc (one file per variable), plus the reference's separate
save_inference_model flow. SURVEY §7.9 asks for *surpassing* this with a
sharded async checkpoint + reshard-on-resume — this module is that
implementation.

TPU-native design: checkpoints are orbax/tensorstore OCDBT trees.
- **Sharded**: each host writes only the array shards it owns; nothing is
  ever gathered to one host (the reference funnels every persistable
  through the trainer-0 executor).
- **Async**: `save(..., asynchronous=True)` returns after enqueueing —
  device arrays are snapshotted, serialization overlaps the next training
  steps (reference saving blocks the trainer).
- **Reshard-on-load**: restore takes the *target* layout (mesh +
  PartitionSpecs), not the saved one; a checkpoint written on a
  dp4×mp2 mesh restores onto dp2×mp4 (or a single chip) with each
  device reading exactly its slice.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["save", "load", "wait", "save_train_step", "load_train_step",
           "latest_step", "Checkpointer"]


def _ocp():
    import orbax.checkpoint as ocp
    return ocp


class Checkpointer:
    """Process-wide async checkpointer (one background serialization
    thread; concurrent saves to different paths queue behind it)."""

    _instance: Optional["Checkpointer"] = None

    def __init__(self):
        ocp = _ocp()
        self._async = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
        self._sync = ocp.PyTreeCheckpointer()

    @classmethod
    def instance(cls) -> "Checkpointer":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def save(self, path: str, state, asynchronous: bool = True):
        path = os.path.abspath(path)
        ckptr = self._async if asynchronous else self._sync
        ckptr.save(path, state, force=True)

    def wait(self):
        self._async.wait_until_finished()

    def restore(self, path: str, target=None):
        ocp = _ocp()
        path = os.path.abspath(path)
        if target is None:
            return self._sync.restore(path)
        restore_args = ocp.checkpoint_utils.construct_restore_args(target)
        return self._sync.restore(path, restore_args=restore_args)


def save(state: Dict[str, Any], path: str, asynchronous: bool = True):
    """Sharded save of a pytree of (possibly distributed) arrays.

    With ``asynchronous=True`` (default) the call returns once device
    arrays are snapshotted; call :func:`wait` to block until the files are
    durable (done automatically before the next save of the same
    checkpointer)."""
    Checkpointer.instance().save(path, state, asynchronous)


def wait():
    """Block until all pending async saves are durable on disk."""
    Checkpointer.instance().wait()


def load(path: str, target=None):
    """Restore a checkpoint.

    ``target`` (optional) is a pytree of arrays or ShapeDtypeStructs
    declaring the desired dtypes AND shardings — arrays restore directly
    into that layout (reshard-on-load). Without it, arrays restore with
    their saved shardings (requires the same topology)."""
    return Checkpointer.instance().restore(path, target)


def latest_step(root: str) -> Optional[int]:
    """Highest numeric subdirectory of ``root`` (step_<N> convention)."""
    if not os.path.isdir(root):
        return None
    steps = []
    for name in os.listdir(root):
        if name.startswith("step_"):
            try:
                steps.append(int(name.split("_", 1)[1]))
            except ValueError:
                pass
    return max(steps) if steps else None


# -- TrainStep integration ---------------------------------------------------


def _listify(tree):
    """Tuples -> lists recursively: orbax round-trips tuple nodes as
    lists, so both the saved state and the restore target use lists and
    the caller rebuilds its native structure afterwards."""
    if isinstance(tree, (tuple, list)):
        return [_listify(x) for x in tree]
    if isinstance(tree, dict):
        return {k: _listify(v) for k, v in tree.items()}
    return tree


def _train_step_target(step) -> Dict[str, Any]:
    """Target pytree for restoring INTO a TrainStep's current layout: every
    array leaf becomes a ShapeDtypeStruct carrying the step's mesh +
    PartitionSpec — the reshard-on-load declaration."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = step.mesh

    def sds(a, spec):
        if not hasattr(a, "shape") or getattr(a, "ndim", 0) is None:
            return a
        if mesh is None:
            return jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype)
        return jax.ShapeDtypeStruct(
            np.shape(a), np.asarray(a).dtype if not hasattr(a, "dtype")
            else a.dtype, sharding=NamedSharding(mesh, spec or P()))

    specs = step._param_specs() if mesh is not None else {}
    frozen_specs = {}
    if mesh is not None:
        frozen_specs = {k: getattr(p, "spec", None) or P()
                        for k, p in step.layer.named_parameters()
                        if k not in step.params}

    target = {
        "params": {k: sds(v, specs.get(k))
                   for k, v in step.params.items()},
        "frozen": {k: sds(v, frozen_specs.get(k))
                   for k, v in step.frozen.items()},
        "buffers": {k: sds(v, None) for k, v in step.buffers.items()},
        "opt_state": {
            k: jax.tree_util.tree_map(
                lambda a, k=k: sds(
                    a, step._slot_spec(k, np.shape(a))
                    if mesh is not None and getattr(a, "ndim", 0) > 0
                    else None)
                if hasattr(a, "shape") else a, v)
            for k, v in step.opt_state.items()},
        "step_count": 0,
        # orbax round-trips tuples as lists; declare a list on both sides
        "rng_state": [0, 0],
        "lr": 0.0,
    }
    if mesh is not None:
        step._specs = specs
    return _listify(target)


def save_train_step(step, path: str, asynchronous: bool = True):
    """Sharded (async) save of a TrainStep's full training state — params,
    frozen params, buffers, optimizer slots, step count, RNG, LR. The
    distributed analogue of TrainStep.save (whole-state pickle)."""
    from ...core.random import default_generator

    state = {
        "params": dict(step.params),
        "frozen": dict(step.frozen),
        "buffers": dict(step.buffers),
        "opt_state": step.opt_state,
        "step_count": step.step_count,
        "rng_state": [int(x) for x in default_generator().get_state()],
        "lr": float(step.optimizer.get_lr()),
    }
    save(_listify(state), path, asynchronous=asynchronous)


def load_train_step(step, path: str):
    """Restore a sharded checkpoint INTO a TrainStep, resharding every
    array to the step's *current* mesh/PartitionSpec layout (which may be
    a different factorization — or single-chip — than at save time)."""
    from ...core.random import default_generator

    target = _train_step_target(step)
    state = load(path, target=target)

    # Re-materialize every restored leaf into a fresh framework-owned
    # device buffer (sharding-preserving). The restore hands back arrays
    # whose storage the checkpoint layer owns; feeding those straight into
    # the TrainStep's donated executable makes XLA free/alias foreign
    # buffers — a hard crash (SIGSEGV on XLA:CPU) on the first step after
    # a reshard-on-load. One copy per leaf at restore time is noise next
    # to checkpoint I/O.
    def _own(a):
        return jnp.copy(a) if isinstance(a, jax.Array) else a

    step.params = jax.tree_util.tree_map(_own, dict(state["params"]))
    step.frozen = jax.tree_util.tree_map(_own, dict(state["frozen"]))
    step.buffers = jax.tree_util.tree_map(_own, dict(state["buffers"]))
    # rebuild the optimizer's native container structure (listified for
    # serialization) from the restored leaves
    step.opt_state = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(step.opt_state),
        jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(_own, state["opt_state"])))
    step.step_count = int(state["step_count"])
    # restore starts a fresh gradient-accumulation window
    step._acc_grads = None
    step._micro_count = 0
    rng = state.get("rng_state")
    if rng is not None:
        default_generator().set_state(tuple(int(x) for x in rng))
    lr = state.get("lr")
    if lr is not None and hasattr(step.optimizer, "set_lr"):
        try:
            step.optimizer.set_lr(float(lr))
        except Exception:
            pass
    step.sync_to_layer()
    return step
