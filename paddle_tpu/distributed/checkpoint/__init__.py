"""Distributed (sharded, async, reshardable, atomically committed)
checkpointing.

reference parity: fleet.save_persistables / fleet_base.py:779 (per-variable
persistable save through the executor), operators/save_op.cc /
load_op.cc (one file per variable), plus the reference's separate
save_inference_model flow. SURVEY §7.9 asks for *surpassing* this with a
sharded async checkpoint + reshard-on-resume — this module is that
implementation.

TPU-native design: checkpoints are orbax/tensorstore OCDBT trees.
- **Sharded**: each host writes only the array shards it owns; nothing is
  ever gathered to one host (the reference funnels every persistable
  through the trainer-0 executor).
- **Async**: `save(..., asynchronous=True)` returns after enqueueing —
  device arrays are snapshotted, serialization overlaps the next training
  steps (reference saving blocks the trainer).
- **Reshard-on-load**: restore takes the *target* layout (mesh +
  PartitionSpecs), not the saved one; a checkpoint written on a
  dp4×mp2 mesh restores onto dp2×mp4 (or a single chip) with each
  device reading exactly its slice.
- **Atomic commit** (CheckFreq-style, docs/FAULT_TOLERANCE.md): every
  save serializes into ``<path>.tmp``, then a *commit* writes an
  fsync'd manifest (per-leaf tree paths/dtypes/shapes, per-file sizes +
  CRC32s, step, flags fingerprint) and atomically renames the staging
  dir onto ``<path>``. A process killed mid-save leaves only a ``.tmp``
  dir — :func:`latest_step` and :func:`load` skip uncommitted or
  verification-failing directories (``FLAGS_checkpoint_verify``:
  off|manifest|full) and fall back to the newest *valid* checkpoint,
  recording a ``checkpoint_fallback`` flight-recorder event.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["save", "load", "wait", "save_train_step", "load_train_step",
           "latest_step", "checkpoint_steps", "verify_checkpoint",
           "Checkpointer", "CheckpointError", "MANIFEST_NAME",
           "STAGING_SUFFIX", "CheckpointManager", "PreemptionSignal"]

logger = logging.getLogger("paddle_tpu.checkpoint")

MANIFEST_NAME = "paddle_tpu_manifest.json"
STAGING_SUFFIX = ".tmp"
REPLACED_SUFFIX = ".old"    # being-replaced checkpoint parked here for
                            # the two renames of a same-path re-commit


class CheckpointError(RuntimeError):
    """A checkpoint save failed or a restore target failed verification."""


def _ocp():
    import orbax.checkpoint as ocp
    return ocp


# ---------------------------------------------------------------------------
# Commit protocol
# ---------------------------------------------------------------------------

def _leaf_manifest(state) -> Dict[str, dict]:
    """Host-side metadata of every array leaf (no device sync): tree
    path -> {shape, dtype}. Scalars/strings are recorded by type."""
    leaves = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        key = jax.tree_util.keystr(path)
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            leaves[key] = {"shape": list(np.shape(leaf)),
                           "dtype": str(leaf.dtype)}
        else:
            leaves[key] = {"type": type(leaf).__name__}
    return leaves


def _flags_fingerprint() -> Dict[str, Any]:
    """Full flags snapshot at save time: a resume under different flags
    (layouts, chunking) is a legitimate thing to want to know post-hoc."""
    try:
        from ...core import flags as F
        out = {}
        for name in sorted(F._REGISTRY):
            try:
                v = F.get_flag(name)
            except Exception:
                continue
            out[name] = v if isinstance(v, (bool, int, float, str,
                                            type(None))) else repr(v)
        return out
    except Exception:
        return {}


def _crc32_file(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                return crc
            crc = zlib.crc32(chunk, crc)


def _file_entries(root: str, checksum: bool = True) -> Dict[str, dict]:
    files = {}
    for dirpath, _dirs, names in os.walk(root):
        for name in names:
            if dirpath == root and name == MANIFEST_NAME:
                continue
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, root)
            entry = {"size": os.path.getsize(full)}
            if checksum:
                entry["crc32"] = _crc32_file(full)
            files[rel] = entry
    return files


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass          # some filesystems refuse dir fsync; rename is
    finally:          # still ordered after the manifest's file fsync
        os.close(fd)


def _record_event(event: str, **fields) -> None:
    """Flight-recorder event, gated exactly like TrainStep records."""
    try:
        from ...monitor.flight_recorder import safe_record_event
    except Exception:
        return
    safe_record_event(event, **fields)


def _commit(tmp: str, final: str, leaves: Dict[str, dict],
            extra_files: Optional[Dict[str, str]],
            step: Optional[int]) -> None:
    """Turn a finished staging dir into a committed checkpoint: write
    extra files + manifest (fsync'd), then atomically rename. A crash at
    ANY point before the rename leaves only the ``.tmp`` dir, which
    every reader skips. When a structured step trace is active
    (FLAGS_trace) the commit appears as a ``checkpoint.commit`` span."""
    try:
        from ...monitor import trace as _trace_mod
        span = _trace_mod.maybe_span("checkpoint.commit", step=step,
                                     path=final)
    except Exception:
        import contextlib
        span = contextlib.nullcontext()
    with span:
        _commit_impl(tmp, final, leaves, extra_files, step)


def _commit_impl(tmp: str, final: str, leaves: Dict[str, dict],
                 extra_files: Optional[Dict[str, str]],
                 step: Optional[int]) -> None:
    from ...testing import chaos

    for name, data in (extra_files or {}).items():
        p = os.path.join(tmp, name)
        with open(p, "w") as f:
            f.write(data)
        _fsync_file(p)
    # CRC32s require re-reading the whole staged tree on the training
    # thread — only pay that when the configured verify level will
    # actually use them. A manifest without CRCs still verifies at
    # 'manifest' (sizes) and 'full' skips absent checksums.
    try:
        from ...core.flags import get_flag
        checksum = get_flag("checkpoint_verify") == "full"
    except Exception:
        checksum = False
    files = _file_entries(tmp, checksum=checksum)
    manifest = {"format": 1,
                "step": step,
                "created": time.time(),
                "flags": _flags_fingerprint(),
                "leaves": leaves,
                "files": files}
    mpath = os.path.join(tmp, MANIFEST_NAME)
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    if chaos.active():
        # torn write racing the commit: a data file loses its tail AFTER
        # its checksum was recorded — verification must catch this
        if chaos.probe("ckpt.write.torn") and files:
            victim = max(files, key=lambda r: files[r]["size"])
            vp = os.path.join(tmp, victim)
            with open(vp, "r+b") as f:
                f.truncate(max(0, files[victim]["size"] // 2))
        if chaos.probe("ckpt.manifest.corrupt"):
            with open(mpath, "wb") as f:
                f.write(b"\x00garbage\x00" * 4)
    _fsync_dir(tmp)
    # Replacing an existing committed checkpoint must not open a window
    # where a crash leaves NOTHING valid: rename the old one aside
    # (readers skip the .old name), swap the new one in, then delete.
    # A crash between the two renames hides the old step (its content
    # survives on disk under .old) — a two-syscall window, versus the
    # whole rmtree of a multi-GB tree if we deleted first.
    old = None
    if os.path.exists(final):
        old = final + REPLACED_SUFFIX
        if os.path.isdir(old):
            shutil.rmtree(old)
        elif os.path.exists(old):
            os.remove(old)
        os.rename(final, old)
    os.rename(tmp, final)
    _fsync_dir(os.path.dirname(final) or ".")
    if old is not None:
        shutil.rmtree(old, ignore_errors=True)
    total = sum(e["size"] for e in files.values())
    _record_event("checkpoint_commit", path=final, step=step,
                  files=len(files), bytes=total)
    logger.info("checkpoint committed: %s (%d files, %d bytes)",
                final, len(files), total)


def verify_checkpoint(path: str, level: Optional[str] = None) \
        -> Optional[str]:
    """Validate a committed checkpoint directory. Returns None when
    valid, else a human-readable reason. ``level`` defaults to
    ``FLAGS_checkpoint_verify`` (off|manifest|full)."""
    if level is None:
        from ...core.flags import get_flag
        level = get_flag("checkpoint_verify")
    if not os.path.isdir(path):
        return "missing (not a directory)"
    if level == "off":
        return None
    mpath = os.path.join(path, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return "uncommitted (no manifest)"
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        files = manifest["files"]
    except (ValueError, KeyError, OSError) as e:
        return f"manifest unreadable ({type(e).__name__}: {e})"
    for rel, entry in files.items():
        full = os.path.join(path, rel)
        if not os.path.exists(full):
            return f"file missing: {rel}"
        size = os.path.getsize(full)
        if size != entry.get("size"):
            return (f"torn file: {rel} is {size} bytes, manifest says "
                    f"{entry.get('size')}")
        if level == "full" and "crc32" in entry:
            if _crc32_file(full) != entry["crc32"]:
                return f"checksum mismatch: {rel}"
    return None


def read_manifest(path: str) -> Optional[dict]:
    """The committed manifest of a checkpoint dir, or None."""
    try:
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# Checkpointer
# ---------------------------------------------------------------------------

class Checkpointer:
    """Process-wide async checkpointer (one background serialization
    thread; concurrent saves to different paths queue behind it).

    Commit discipline: async saves serialize into ``<path>.tmp`` and are
    committed (manifest + rename) by :meth:`wait` — a checkpoint is
    durable-and-visible only after ``wait()`` returns. ``wait`` and the
    next ``save`` RE-RAISE background-save failures as
    :class:`CheckpointError`; a failed save can never silently pass for
    a checkpoint."""

    _instance: Optional["Checkpointer"] = None

    def __init__(self):
        ocp = _ocp()
        self._async = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
        self._sync = ocp.PyTreeCheckpointer()
        # the one async save awaiting commit: (tmp, final, leaves,
        # extra_files, step). At most ONE can be outstanding — save()
        # finalizes any pending entry before enqueueing (the async
        # checkpointer serializes behind one thread anyway).
        self._pending: Optional[Tuple[str, str, dict, Optional[dict],
                                      Optional[int]]] = None

    @classmethod
    def instance(cls) -> "Checkpointer":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def save(self, path: str, state, asynchronous: bool = True,
             extra_files: Optional[Dict[str, str]] = None,
             step: Optional[int] = None):
        # a still-pending (or failed) earlier save is finalized first:
        # its staging dir may be THIS path's, and its failure must
        # surface here rather than evaporate
        if self._pending:
            self.wait()
        path = os.path.abspath(path)
        tmp = path + STAGING_SUFFIX
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)          # orphan from a killed process
        leaves = _leaf_manifest(state)
        if asynchronous:
            self._async.save(tmp, state, force=True)
            self._pending = (tmp, path, leaves, extra_files, step)
        else:
            self._sync.save(tmp, state, force=True)
            _commit(tmp, path, leaves, extra_files, step)

    def pending_ready(self) -> bool:
        """True when the pending async save has FINISHED serializing, so
        :meth:`wait` would commit without blocking. Best-effort probe of
        the orbax background thread (private attr, pinned version) —
        False when there is nothing pending or the answer is unknown.
        Lets the training loop commit at the first step boundary after
        serialization completes instead of at the next interval
        (CheckFreq: worst-case loss = one interval, not two)."""
        if self._pending is None:
            return False
        try:
            thread = getattr(self._async, "_thread", None)
            return thread is None or not thread.is_alive()
        except Exception:
            return False

    def wait(self):
        """Block until the pending async save is durable AND committed.
        Re-raises any background serialization/commit failure — the run
        must not continue believing it has a checkpoint it doesn't."""
        pending, self._pending = self._pending, None
        try:
            self._async.wait_until_finished()
            if hasattr(self._async, "check_for_errors"):
                self._async.check_for_errors()
        except Exception as e:
            if pending is not None:
                shutil.rmtree(pending[0], ignore_errors=True)
            raise CheckpointError(
                f"async checkpoint save failed: {e!r} (staging dir "
                "removed; the previous committed checkpoint is intact)"
            ) from e
        if pending is None:
            return
        tmp, final, leaves, extra_files, step = pending
        try:
            _commit(tmp, final, leaves, extra_files, step)
        except Exception as e:
            shutil.rmtree(tmp, ignore_errors=True)
            raise CheckpointError(
                f"checkpoint commit failed: {final}: {e!r}") from e

    def restore(self, path: str, target=None):
        ocp = _ocp()
        path = os.path.abspath(path)
        reason = verify_checkpoint(path)
        if reason is not None:
            raise CheckpointError(
                f"refusing to restore {path}: {reason}. Use "
                "latest_step()/CheckpointManager.resume() for automatic "
                "fallback to the newest valid checkpoint, or "
                "FLAGS_checkpoint_verify=off for legacy dirs.")
        if target is None:
            return self._sync.restore(path)
        restore_args = ocp.checkpoint_utils.construct_restore_args(target)
        return self._sync.restore(path, restore_args=restore_args)


def save(state: Dict[str, Any], path: str, asynchronous: bool = True,
         extra_files: Optional[Dict[str, str]] = None,
         step: Optional[int] = None):
    """Sharded save of a pytree of (possibly distributed) arrays.

    With ``asynchronous=True`` (default) the call returns once device
    arrays are snapshotted; call :func:`wait` to block until the files
    are durable AND the checkpoint is committed (manifest + atomic
    rename — done automatically before the next save of the same
    checkpointer). ``extra_files`` (name -> text) are committed inside
    the checkpoint dir and covered by the manifest."""
    Checkpointer.instance().save(path, state, asynchronous,
                                 extra_files=extra_files, step=step)


def wait():
    """Block until all pending async saves are durable on disk and
    committed; re-raises background-save failures."""
    Checkpointer.instance().wait()


def load(path: str, target=None):
    """Restore a checkpoint (verification per FLAGS_checkpoint_verify
    runs first; an uncommitted/torn dir raises CheckpointError).

    ``target`` (optional) is a pytree of arrays or ShapeDtypeStructs
    declaring the desired dtypes AND shardings — arrays restore directly
    into that layout (reshard-on-load). Without it, arrays restore with
    their saved shardings (requires the same topology)."""
    return Checkpointer.instance().restore(path, target)


def checkpoint_steps(root: str) -> List[int]:
    """Committed ``step_<N>`` directory numbers under ``root``
    (ascending; staging ``.tmp`` dirs excluded, validity NOT checked)."""
    if not os.path.isdir(root):
        return []
    steps = []
    for name in os.listdir(root):
        if not name.startswith("step_") or name.endswith(STAGING_SUFFIX):
            continue
        try:
            steps.append(int(name.split("_", 1)[1]))
        except ValueError:
            pass
    return sorted(steps)


def latest_step(root: str) -> Optional[int]:
    """Highest *valid* ``step_<N>`` checkpoint under ``root``.

    Uncommitted (``.tmp`` / manifest-less) and verification-failing
    directories are skipped with a ``checkpoint_fallback`` flight event
    and a warning — the torn last save of a killed run must never be the
    resume point."""
    skipped = []
    for n in reversed(checkpoint_steps(root)):
        path = os.path.join(root, f"step_{n}")
        reason = verify_checkpoint(path)
        if reason is None:
            for bad_n, bad_reason in skipped:
                _record_event("checkpoint_fallback", step=bad_n,
                              reason=bad_reason, fallback_to=n)
            return n
        skipped.append((n, reason))
        logger.warning("skipping invalid checkpoint %s: %s", path, reason)
    for bad_n, bad_reason in skipped:
        _record_event("checkpoint_fallback", step=bad_n,
                      reason=bad_reason, fallback_to=None)
    return None


# -- TrainStep integration ---------------------------------------------------


def _listify(tree):
    """Tuples -> lists recursively: orbax round-trips tuple nodes as
    lists, so both the saved state and the restore target use lists and
    the caller rebuilds its native structure afterwards."""
    if isinstance(tree, (tuple, list)):
        return [_listify(x) for x in tree]
    if isinstance(tree, dict):
        return {k: _listify(v) for k, v in tree.items()}
    return tree


def _train_step_target(step) -> Dict[str, Any]:
    """Target pytree for restoring INTO a TrainStep's current layout: every
    array leaf becomes a ShapeDtypeStruct carrying the step's mesh +
    PartitionSpec — the reshard-on-load declaration."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = step.mesh

    def sds(a, spec):
        if not hasattr(a, "shape") or getattr(a, "ndim", 0) is None:
            return a
        if mesh is None:
            return jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype)
        return jax.ShapeDtypeStruct(
            np.shape(a), np.asarray(a).dtype if not hasattr(a, "dtype")
            else a.dtype, sharding=NamedSharding(mesh, spec or P()))

    specs = step._param_specs() if mesh is not None else {}
    frozen_specs = {}
    if mesh is not None:
        frozen_specs = {k: getattr(p, "spec", None) or P()
                        for k, p in step.layer.named_parameters()
                        if k not in step.params}

    target = {
        "params": {k: sds(v, specs.get(k))
                   for k, v in step.params.items()},
        "frozen": {k: sds(v, frozen_specs.get(k))
                   for k, v in step.frozen.items()},
        "buffers": {k: sds(v, None) for k, v in step.buffers.items()},
        "opt_state": {
            k: jax.tree_util.tree_map(
                lambda a, k=k: sds(
                    a, step._slot_spec(k, np.shape(a))
                    if mesh is not None and getattr(a, "ndim", 0) > 0
                    else None)
                if hasattr(a, "shape") else a, v)
            for k, v in step.opt_state.items()},
        "step_count": 0,
        # orbax round-trips tuples as lists; declare a list on both sides
        "rng_state": [0, 0],
        "lr": 0.0,
    }
    if mesh is not None:
        step._specs = specs
    return _listify(target)


def save_train_step(step, path: str, asynchronous: bool = True,
                    extra_files: Optional[Dict[str, str]] = None):
    """Sharded (async) save of a TrainStep's full training state — params,
    frozen params, buffers, optimizer slots, step count, RNG, LR. The
    distributed analogue of TrainStep.save (whole-state pickle)."""
    from ...core.random import default_generator

    state = {
        "params": dict(step.params),
        "frozen": dict(step.frozen),
        "buffers": dict(step.buffers),
        "opt_state": step.opt_state,
        "step_count": step.step_count,
        "rng_state": [int(x) for x in default_generator().get_state()],
        "lr": float(step.optimizer.get_lr()),
    }
    save(_listify(state), path, asynchronous=asynchronous,
         extra_files=extra_files, step=int(step.step_count))


def load_train_step(step, path: str):
    """Restore a sharded checkpoint INTO a TrainStep, resharding every
    array to the step's *current* mesh/PartitionSpec layout (which may be
    a different factorization — or single-chip — than at save time)."""
    from ...core.random import default_generator

    target = _train_step_target(step)
    state = load(path, target=target)

    # Re-materialize every restored leaf into a fresh framework-owned
    # device buffer (sharding-preserving). The restore hands back arrays
    # whose storage the checkpoint layer owns; feeding those straight into
    # the TrainStep's donated executable makes XLA free/alias foreign
    # buffers — a hard crash (SIGSEGV on XLA:CPU) on the first step after
    # a reshard-on-load. One copy per leaf at restore time is noise next
    # to checkpoint I/O.
    def _own(a):
        return jnp.copy(a) if isinstance(a, jax.Array) else a

    step.params = jax.tree_util.tree_map(_own, dict(state["params"]))
    step.frozen = jax.tree_util.tree_map(_own, dict(state["frozen"]))
    step.buffers = jax.tree_util.tree_map(_own, dict(state["buffers"]))
    # rebuild the optimizer's native container structure (listified for
    # serialization) from the restored leaves
    step.opt_state = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(step.opt_state),
        jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(_own, state["opt_state"])))
    step.step_count = int(state["step_count"])
    # restore starts a fresh gradient-accumulation window
    step._acc_grads = None
    step._micro_count = 0
    rng = state.get("rng_state")
    if rng is not None:
        default_generator().set_state(tuple(int(x) for x in rng))
    lr = state.get("lr")
    if lr is not None and hasattr(step.optimizer, "set_lr"):
        try:
            step.optimizer.set_lr(float(lr))
        except Exception:
            pass
    step.sync_to_layer()
    return step


from .manager import CheckpointManager, PreemptionSignal  # noqa: E402,F401
