"""CheckpointManager: the auto-resume driver over atomic checkpoints.

reference parity: ``paddle.distributed.fleet.elastic`` pairs its restart
supervisor with ``fleet.save_persistables`` called "often enough";
PaddlePaddle's auto-checkpoint (``paddle.fluid.incubate.checkpoint``)
wraps the train loop to save/restore on a cadence. MegaScale/CheckFreq
economics say the same thing: recovery time = (interval since last
commit) + (restore time), so checkpoints must be frequent, asynchronous,
*and* atomically committed — this manager is that loop driver for the
TPU-native stack:

- **interval saves**: ``on_step()`` after every optimizer step commits a
  sharded async checkpoint of the FULL training state — TrainStep params/
  opt-state/step count, the process RNG stream, and the caller's
  dataloader position (epoch/offset) — every ``interval_steps`` steps
  into ``<root>/step_<N>`` via the atomic commit protocol;
- **preemption**: a SIGTERM (the cloud preemption signal) is latched by
  a handler and honoured at the NEXT step boundary: a final synchronous
  checkpoint is committed, then :class:`PreemptionSignal` is raised so
  the supervisor (elastic restart, the scheduler's replacement pod) can
  resume with nothing lost;
- **resume()**: restores the newest *valid* checkpoint into the
  TrainStep (reshard-on-load), skipping torn/uncommitted directories
  with a ``checkpoint_fallback`` flight event, and hands back the saved
  dataloader position — training state after resume is bit-exact with
  the uninterrupted run (tests/test_fault_tolerance.py pins this);
- **retention**: ``keep_n`` newest valid checkpoints survive GC; the
  last valid checkpoint is never deleted, whatever ``keep_n`` says.
"""

from __future__ import annotations

import json
import logging
import os
import signal as signal_mod
import time
from typing import Any, Dict, Optional

logger = logging.getLogger("paddle_tpu.checkpoint")

MANAGER_STATE_NAME = "manager_state.json"


class PreemptionSignal(Exception):
    """Raised by ``on_step`` after a latched SIGTERM has been honoured
    with a final committed checkpoint; carries the checkpoint path."""

    def __init__(self, message: str, path: Optional[str] = None,
                 step: Optional[int] = None):
        super().__init__(message)
        self.path = path
        self.step = step


class CheckpointManager:
    """Drive interval/preemption checkpointing and resume for one
    TrainStep. Use as a context manager (restores signal handlers on
    exit) or call :meth:`close` explicitly::

        with CheckpointManager(step, root, interval_steps=50) as mgr:
            start = mgr.resume() or {}
            for i in range(start.get("step", 0), total_steps):
                loss = step(*batch(i))
                mgr.on_step(dataloader_state={"offset": i + 1})
    """

    def __init__(self, train_step, root: str, interval_steps: int = 100,
                 keep_n: int = 3, asynchronous: bool = True,
                 preempt_signals=(signal_mod.SIGTERM,)):
        self._step = train_step
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.interval_steps = max(1, int(interval_steps))
        self.keep_n = max(1, int(keep_n))
        self.asynchronous = asynchronous
        self._preempt: Optional[int] = None
        self._old_handlers: Dict[int, Any] = {}
        self._dataloader_state: Optional[dict] = None
        self.save_count = 0
        for sig in preempt_signals or ():
            try:
                self._old_handlers[sig] = signal_mod.signal(
                    sig, self._on_signal)
            except (ValueError, OSError):
                # non-main thread or unsupported signal: interval saves
                # still work, preemption latching is unavailable
                logger.warning("CheckpointManager: cannot install "
                               "handler for signal %s", sig)

    # -- signal latch ------------------------------------------------------
    def _on_signal(self, signum, frame):
        # handlers must be async-signal-thin: latch and return. The next
        # on_step() boundary commits the final checkpoint — committing
        # HERE could catch the training step mid-update.
        self._preempt = signum

    @property
    def preempted(self) -> bool:
        return self._preempt is not None

    # -- paths -------------------------------------------------------------
    def step_dir(self, n: int) -> str:
        return os.path.join(self.root, f"step_{int(n)}")

    # -- saving ------------------------------------------------------------
    def save(self, asynchronous: Optional[bool] = None,
             dataloader_state: Optional[dict] = None) -> str:
        """Commit a checkpoint of the current training state (returns
        the final committed path). Synchronous saves are durable on
        return; async saves are durable after the next ``wait()``/save
        (or the final preemption commit)."""
        from ...monitor import goodput as _goodput
        from . import save_train_step
        if dataloader_state is not None:
            self._dataloader_state = dataloader_state
        n = int(self._step.step_count)
        path = self.step_dir(n)
        state = {
            "step": n,
            "saved_at": time.time(),
            "dataloader": self._dataloader_state,
        }
        led = _goodput.active_ledger()
        if led is not None:
            # the goodput ledger rides the sidecar: train_goodput_pct
            # survives SIGTERM → resume, and resume() attributes the
            # restart gap (docs/OBSERVABILITY.md)
            state["goodput"] = led.state()
        sidecar = json.dumps(state, indent=1)
        asynchronous = (self.asynchronous if asynchronous is None
                        else asynchronous)
        # sync saves (interval sync mode, the preemption final commit)
        # block training — checkpoint_stall badput. Async enqueue time
        # is accounted too: near-zero when healthy, and a torn/stuck
        # write surfaces in the same bucket instead of vanishing.
        with _goodput.measure("checkpoint_stall"):
            save_train_step(self._step, path, asynchronous=asynchronous,
                            extra_files={MANAGER_STATE_NAME: sidecar})
        if not asynchronous:
            self.gc()
        self.save_count += 1
        return path

    def wait(self) -> None:
        """Finalize pending async saves (commit + error propagation)."""
        from ...monitor import goodput as _goodput
        from . import wait as ckpt_wait
        with _goodput.measure("checkpoint_stall"):
            ckpt_wait()

    def on_step(self, dataloader_state: Optional[dict] = None) \
            -> Optional[str]:
        """Call once per optimizer step, after the step. Honours a
        latched preemption (final sync commit, then raises
        :class:`PreemptionSignal`), else saves every ``interval_steps``
        steps. Returns the checkpoint path when one was enqueued."""
        from ...testing import chaos
        if dataloader_state is not None:
            self._dataloader_state = dataloader_state
        if chaos.active() and chaos.probe("worker.die"):
            raise chaos.ChaosFault(
                "worker.die",
                f"chaos: worker died at step {self._step.step_count}")
        if self._preempt is not None:
            signum = self._preempt
            # a FAILED earlier async save must not abort the final
            # commit: drain (and log) pending failures first, then the
            # sync save below starts from a clean checkpointer — the
            # grace period's one job is committing the current state
            try:
                self.wait()
            except Exception as e:
                logger.warning("preemption: pending async save had "
                               "failed (%r); attempting the final "
                               "commit anyway", e)
            path = self.save(asynchronous=False)
            from . import _record_event
            _record_event("preempted", signal=int(signum),
                          step=int(self._step.step_count), path=path)
            logger.warning("preemption (signal %s): final checkpoint "
                           "committed at %s", signum, path)
            raise PreemptionSignal(
                f"preempted by signal {signum}; final checkpoint "
                f"committed at {path}", path=path,
                step=int(self._step.step_count))
        if (self._step.step_count
                and self._step.step_count % self.interval_steps == 0):
            path = self.save()
            if self.asynchronous:
                # commit + GC of the PREVIOUS interval's save happened at
                # this save's enqueue (Checkpointer serializes); GC here
                # covers sync mode and bounded-disk long runs
                self.gc()
            return path
        from . import Checkpointer
        if Checkpointer.instance().pending_ready():
            # the previous interval's async serialization has finished:
            # commit NOW (checksum-free manifest + rename — cheap) at
            # this step boundary instead of at the next interval, so the
            # worst-case loss on a SIGKILL is ONE interval, not two
            self.wait()
            self.gc()
        return None

    # -- resume ------------------------------------------------------------
    def resume(self) -> Optional[dict]:
        """Restore the newest valid checkpoint into the TrainStep.
        Returns ``{"step", "path", "dataloader"}`` or None when no valid
        checkpoint exists. Invalid/torn directories and restore failures
        fall back to the next-newest valid checkpoint (each skip is a
        ``checkpoint_fallback`` flight event)."""
        from . import (_record_event, checkpoint_steps, load_train_step,
                       verify_checkpoint)
        # fallback events are back-filled with the step actually resumed
        # from (same semantics as latest_step): the recovery timeline
        # must show where each skip landed, not a fallback to nowhere
        skipped = []
        result = None
        for n in reversed(checkpoint_steps(self.root)):
            path = self.step_dir(n)
            reason = verify_checkpoint(path)
            if reason is None:
                try:
                    load_train_step(self._step, path)
                except Exception as e:
                    reason = f"restore failed: {e!r}"
            if reason is not None:
                logger.warning("resume: skipping %s: %s", path, reason)
                skipped.append((n, reason))
                continue
            meta = self._read_sidecar(path)
            self._dataloader_state = (meta or {}).get("dataloader")
            saved_goodput = (meta or {}).get("goodput")
            if saved_goodput:
                from ...monitor import goodput as _goodput
                led = _goodput.active_ledger()
                if led is not None:
                    # carry the previous incarnation's bucket totals
                    # forward and attribute the dead time since its
                    # final commit to restart_gap
                    gap = led.restore(saved_goodput)
                    logger.info("goodput ledger restored (restart gap "
                                "%.1fs attributed)", gap)
            logger.info("resumed from %s (step %d)", path, n)
            result = {"step": n, "path": path,
                      "dataloader": self._dataloader_state}
            break
        for bad_n, bad_reason in skipped:
            _record_event("checkpoint_fallback", step=bad_n,
                          reason=bad_reason,
                          fallback_to=result["step"] if result else None)
        return result

    @staticmethod
    def _read_sidecar(path: str) -> Optional[dict]:
        try:
            with open(os.path.join(path, MANAGER_STATE_NAME)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    # -- retention ---------------------------------------------------------
    def gc(self) -> None:
        """Delete committed-valid checkpoints beyond the ``keep_n``
        newest, plus orphaned staging dirs. Never deletes the last valid
        checkpoint; invalid committed dirs are left for forensics.

        Retention only needs committed-vs-torn, so validity is checked
        at ``manifest`` level (stat-only) regardless of
        ``FLAGS_checkpoint_verify`` — under ``full`` the global level
        would re-checksum every retained checkpoint inside the training
        loop at every interval save."""
        import shutil
        from . import (REPLACED_SUFFIX, STAGING_SUFFIX, Checkpointer,
                       checkpoint_steps, verify_checkpoint)
        valid = [n for n in reversed(checkpoint_steps(self.root))
                 if verify_checkpoint(self.step_dir(n),
                                      level="manifest") is None]
        for n in valid[self.keep_n:]:
            shutil.rmtree(self.step_dir(n), ignore_errors=True)
            logger.info("checkpoint GC: removed %s", self.step_dir(n))
        p = Checkpointer.instance()._pending
        pending = {p[0]} if p is not None else set()
        for name in os.listdir(self.root):
            # .old = a replaced checkpoint parked aside by a commit that
            # died between its two renames; both kinds are orphans here
            if not name.endswith((STAGING_SUFFIX, REPLACED_SUFFIX)):
                continue
            full = os.path.join(self.root, name)
            if full in pending or not os.path.isdir(full):
                continue
            shutil.rmtree(full, ignore_errors=True)
            logger.info("checkpoint GC: removed orphan staging dir %s",
                        full)

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        """Flush pending saves and restore the original signal
        handlers (idempotent)."""
        try:
            self.wait()
        finally:
            for sig, old in self._old_handlers.items():
                try:
                    signal_mod.signal(sig, old)
                except (ValueError, OSError):
                    pass
            self._old_handlers = {}

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
