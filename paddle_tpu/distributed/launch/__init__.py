"""Distributed launcher.

reference parity: python/paddle/distributed/fleet/launch.py:451 (launch
collective mode: one worker process per device, env-var protocol
PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM/PADDLE_MASTER, log redirection,
failure propagation) and launch_utils.py (TrainerProc bookkeeping).

TPU-native notes: on TPU pods the normal topology is ONE process per host
(JAX SPMD controller per host), so --nproc_per_node defaults to 1 and
--nnodes/--node_rank/--master describe the host fabric; the env protocol
feeds `init_parallel_env` which calls jax.distributed.initialize. Local
multi-process launches (CPU testing, one proc per chip debugging) use
nproc_per_node > 1.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

__all__ = ["launch", "main"]


def _build_env(rank: int, world: int, master: str, port: int,
               local_rank: int, extra=None) -> dict:
    env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_MASTER": master,
        "MASTER_ADDR": master,
        "MASTER_PORT": str(port),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "FLAGS_selected_tpus": str(local_rank),
    })
    if extra:
        env.update(extra)
    return env


def launch(script: str, script_args: List[str], nproc_per_node: int = 1,
           nnodes: int = 1, node_rank: int = 0,
           master: Optional[str] = None, port: int = 12355,
           log_dir: Optional[str] = None) -> int:
    """Start nproc_per_node worker processes running ``script``; block until
    all exit. Returns the first nonzero exit code (0 on success). On any
    worker failure the remaining workers receive SIGTERM — the reference's
    terminate_local_procs behavior (launch_utils.py)."""
    master = master or "127.0.0.1"
    world = nproc_per_node * nnodes
    procs = []
    logs = []
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    for local_rank in range(nproc_per_node):
        rank = node_rank * nproc_per_node + local_rank
        env = _build_env(rank, world, master, port, local_rank)
        if log_dir:
            log_f = open(os.path.join(log_dir, f"workerlog.{local_rank}"),
                         "w")
            logs.append(log_f)
            stdout = stderr = log_f
        else:
            stdout = stderr = None
        cmd = [sys.executable, "-u", script, *script_args]
        procs.append(subprocess.Popen(cmd, env=env, stdout=stdout,
                                      stderr=stderr))

    rc = 0
    try:
        alive = set(range(len(procs)))
        while alive:
            for i in list(alive):
                code = procs[i].poll()
                if code is None:
                    continue
                alive.discard(i)
                if code != 0 and rc == 0:
                    rc = code
                    for j in alive:          # fail fast: stop the rest
                        procs[j].send_signal(signal.SIGTERM)
            time.sleep(0.05)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for f in logs:
            f.close()
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="Launch a distributed training job "
                    "(reference: fleet/launch.py)")
    parser.add_argument("--nproc_per_node", type=int, default=1)
    parser.add_argument("--nnodes", type=int, default=1)
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--master", type=str, default=None,
                        help="coordinator host (default 127.0.0.1)")
    parser.add_argument("--port", type=int, default=12355)
    parser.add_argument("--log_dir", type=str, default=None)
    parser.add_argument("script", type=str)
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    return launch(args.script, args.script_args,
                  nproc_per_node=args.nproc_per_node, nnodes=args.nnodes,
                  node_rank=args.node_rank, master=args.master,
                  port=args.port, log_dir=args.log_dir)
