"""Process groups and functional collectives over XLA.

TPU-native redesign of the reference's collective stack
(reference: python/paddle/distributed/collective.py:41-1577 — Group/new_group
creating NCCL rings via c_comm_init, functional ops appending c_allreduce_* /
c_broadcast / c_allgather / alltoall / send_v2 graph ops; platform
collective_helper.h:68 NCCLCommContext ring registry).

Design (SURVEY.md §5/§7): a *ring* becomes a **named mesh axis**. A
:class:`Group` is a set of device positions with an axis name and a 1-D
sub-mesh; there is no comm-id bootstrap — XLA owns the ICI/DCN transport.

Every functional collective works in TWO contexts:

1. **Traced (inside jit/shard_map)** — the hot path. When the group's axis
   is bound (we track bound axes in `env`), the op lowers straight to the
   XLA collective: ``psum``/``all_gather``/``ppermute``/``all_to_all``.
   The compiler schedules/overlaps them — this replaces comm streams,
   ``c_sync_comm_stream`` and the Reducer.

2. **Eager (single-controller)** — the per-rank view. In the reference each
   rank is a process holding its own tensor; in single-controller JAX the
   per-rank tensors of a group live stacked along a leading axis of one
   array (shape ``[nranks, ...]`` — exactly the layout the reference's
   multi-process tests compare, test_collective_base.py:206). Eager
   collectives shard that axis over the group's mesh and run the real XLA
   collective via ``shard_map`` — the same lowering multi-chip uses.

In true multi-process mode (``jax.distributed`` initialized) the eager ops
on this-process tensors additionally route through multihost utilities.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..core.tensor import Tensor
from ..testing import chaos
from . import env

__all__ = [
    "ReduceOp", "Group", "new_group", "get_group", "destroy_process_group",
    "all_reduce", "all_gather", "all_gather_object", "reduce", "broadcast",
    "scatter", "alltoall", "send", "recv", "barrier", "wait",
    "all_reduce_arrays", "is_initialized", "get_world_size_of_group",
    "CollectiveTimeoutError",
]


class CollectiveTimeoutError(RuntimeError):
    """An eager collective dispatch exceeded ``FLAGS_collective_timeout_s``.

    The reference analogue is an NCCL communicator watchdog abort
    (NCCL_ASYNC_ERROR_HANDLING): a hung ring must become a structured,
    catchable error on the controller instead of a silent stall. Carries
    the op name, group axis and the budget for supervisors that restart
    on comm failure."""

    def __init__(self, op: str, group: "Group", timeout_s: float):
        super().__init__(
            f"collective {op!r} on group {group.axis_name!r} "
            f"(nranks={group.nranks}) did not complete within "
            f"{timeout_s:g}s (FLAGS_collective_timeout_s). The dispatch "
            "thread is abandoned; on a real hang, restart from the last "
            "committed checkpoint (distributed.checkpoint."
            "CheckpointManager).")
        self.op = op
        self.group_axis = group.axis_name
        self.timeout_s = timeout_s


class ReduceOp:
    """reference: collective.py ReduceOp (SUM/MAX/MIN/PROD/AVG)."""
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


_LAX_REDUCE = {
    ReduceOp.SUM: jax.lax.psum,
    ReduceOp.MAX: jax.lax.pmax,
    ReduceOp.MIN: jax.lax.pmin,
}


class Group:
    """A communicator: device positions + named mesh axis (replaces ring_id).

    reference: collective.py:79 Group, :209 new_group (ring creation via
    c_comm_init); here no bootstrap is needed — the axis name keys XLA
    collectives and the 1-D sub-mesh scopes eager emulation.
    """

    def __init__(self, ranks: Sequence[int], gid: int,
                 axis_name: Optional[str] = None, mesh: Optional[Mesh] = None):
        self.ranks = list(ranks)
        self.id = gid
        self.axis_name = axis_name or f"group_{gid}"
        self._mesh = mesh

    @property
    def nranks(self) -> int:
        return len(self.ranks)

    world_size = nranks

    @property
    def mesh(self) -> Mesh:
        if self._mesh is None:
            devices = np.array([jax.devices()[r] for r in self.ranks])
            self._mesh = Mesh(devices, (self.axis_name,))
        return self._mesh

    def get_group_rank(self, global_rank: int) -> int:
        return self.ranks.index(global_rank) if global_rank in self.ranks else -1

    def is_member(self) -> bool:
        return True

    def __repr__(self):
        return f"Group(id={self.id}, axis={self.axis_name!r}, ranks={self.ranks})"


_lock = threading.Lock()
_groups: dict = {}
_next_gid = [1]  # gid 0 is reserved for the world group


def _default_group() -> Group:
    with _lock:
        if 0 not in _groups:
            n = len(jax.devices())
            _groups[0] = Group(list(range(n)), 0, axis_name="world")
    return _groups[0]


def new_group(ranks: Optional[Sequence[int]] = None, backend: Optional[str] = None,
              timeout=None, axis_name: Optional[str] = None) -> Group:
    """Create a communicator over a subset of device positions.

    reference: collective.py:209 new_group — there: ring_id allocation +
    per-rank c_comm_init; here: allocate an id + axis name, done.
    """
    if ranks is None:
        ranks = list(range(len(jax.devices())))
    with _lock:
        gid = _next_gid[0]
        _next_gid[0] += 1
        g = Group(sorted(ranks), gid, axis_name=axis_name)
        _groups[gid] = g
    return g


def get_group(gid: int = 0) -> Group:
    if gid == 0:
        return _default_group()
    return _groups[gid]


def destroy_process_group(group: Optional[Group] = None):
    with _lock:
        if group is None:
            _groups.clear()
            # gid counter stays monotonic: gid 0 remains reserved for the
            # world group so a later new_group can never be mistaken for it
        else:
            _groups.pop(group.id, None)


def is_initialized() -> bool:
    return True


def get_world_size_of_group(group: Optional[Group] = None) -> int:
    return (group or _default_group()).nranks


# ---------------------------------------------------------------------------
# Traced/eager dispatch plumbing
# ---------------------------------------------------------------------------

def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _rewrap(out, like):
    if isinstance(like, Tensor):
        t = Tensor(out, stop_gradient=like.stop_gradient)
        return t
    return out


def _traced_axes(group: Optional[Group]):
    """Return the axis name(s) to use if we're inside a bound trace context."""
    bound = env.bound_axes()
    if not bound:
        return None
    if group is None or group.id == 0:
        return tuple(bound)  # default group = reduce over every bound axis
    if group.axis_name in bound:
        return (group.axis_name,)
    return None


def _is_traced(x) -> bool:
    return isinstance(x, jax.core.Tracer)


_eager_cache: dict = {}


def _eager_shardmap(group: Group, key, body, n_out_stacked=True):
    """jit(shard_map(body)) over the group's 1-D mesh, cached per (group,key).

    The operand's leading axis (length group.nranks) is the per-rank axis;
    each shard sees a [1, ...] local block with the group axis bound.
    """
    ck = (group.id, group.axis_name, group.nranks, key)
    f = _eager_cache.get(ck)
    if f is None:
        ax = group.axis_name
        f = jax.jit(env.shard_map(
            body, mesh=group.mesh, in_specs=P(ax), out_specs=P(ax),
            check_vma=False))
        _eager_cache[ck] = f
    return f


def _eager_warm(group: Group, key) -> bool:
    """Whether this (group, op-key)'s shard_map wrapper is already built.
    Approximate compile detection: a warm wrapper can still trigger an
    XLA compile on a new operand shape, but the common skew source — the
    first call paying jit+compile — is caught."""
    return (group.id, group.axis_name, group.nranks, key) in _eager_cache


@contextlib.contextmanager
def _comm_trace(op: str, group: Group, x, cache_key=None):
    """Comms observability for every eager collective (tentpole pillar 3;
    reference analogue: the NCCL comm events CUPTI puts on the
    device_tracer timeline). Records op name, group axis/size, operand
    bytes and dispatch latency into the monitor registry, and emits a
    ``comm::<op>`` RecordEvent so collectives show up on host timelines
    when a profiler window is open.

    Latency here is DISPATCH latency (time for the XLA call to return,
    enqueue included, device completion not) — the single-controller
    eager model has no per-collective completion event; use
    ``wait``/``block_until_ready`` timings for on-device time. A COLD
    call (shard_map wrapper not built yet) pays trace+compile, orders of
    magnitude above steady-state dispatch — those land in the separate
    ``comm_cold_dispatch_seconds`` histogram so the latency series stays
    readable. Telemetry must never sink the collective itself, hence the
    broad guards."""
    nbytes = int(getattr(x, "nbytes", 0) or 0)
    warm = cache_key is None or _eager_warm(group, cache_key)
    try:
        from ..profiler import RecordEvent
        span = RecordEvent(f"comm::{op}")
    except Exception:
        span = contextlib.nullcontext()
    try:
        # structured-trace child span: attaches under the active
        # train.step trace (FLAGS_trace + TrainStep's activate()); a
        # no-op — no allocation — when no trace is current
        from ..monitor import trace as _trace_mod
        tspan = _trace_mod.maybe_span(
            f"collective::{op}", group=group.axis_name,
            nranks=group.nranks, bytes=nbytes)
    except Exception:
        tspan = contextlib.nullcontext()
    t0 = time.perf_counter()
    with span, tspan:
        yield
    dt = time.perf_counter() - t0
    try:
        from ..monitor import get_registry
        reg = get_registry()
        labels = {"op": op, "group": group.axis_name,
                  "nranks": group.nranks}
        reg.counter("comm_ops_total",
                    "eager collective dispatches").inc(**labels)
        reg.counter("comm_bytes_total",
                    "operand bytes moved through eager collectives"
                    ).inc(nbytes, **labels)
        reg.histogram("comm_latency_seconds" if warm
                      else "comm_cold_dispatch_seconds",
                      "eager collective dispatch latency (warm wrapper)"
                      if warm else
                      "first-call eager collective dispatch incl. "
                      "trace+compile").observe(dt, **labels)
        # crash forensics: collectives land in the flight-recorder event
        # ring too (a run that dies mid-sync should say so in the dump);
        # gated like the TrainStep records — off = zero recorder writes
        from ..monitor import flight_recorder as _flight
        if _flight.enabled():
            _flight.get_flight_recorder().record_event(
                "collective", op=op, group=group.axis_name,
                nranks=group.nranks, bytes=nbytes, dispatch_ms=dt * 1e3)
    except Exception:
        pass


def _run_collective(op: str, group: Group, fn, *args):
    """Dispatch an eager collective under the watchdog.

    With ``FLAGS_collective_timeout_s`` unset (default) and no chaos
    armed this is a direct call — zero overhead. With a budget, the
    dispatch runs on a daemon worker thread and a wall-clock watchdog
    converts a stall into :class:`CollectiveTimeoutError`, recording a
    ``collective_timeout`` flight-recorder event and a registry counter.
    XLA cannot cancel an in-flight collective from python, so the hung
    thread is abandoned (exactly what the NCCL watchdog does before
    aborting the communicator) — the caller's recovery is a restart from
    the last committed checkpoint. The budget covers the whole dispatch,
    including a first-call trace+compile; set it well above cold-start.

    Chaos site ``collective.hang`` blocks the worker (bounded,
    cancellable) to prove the watchdog path deterministically."""
    from ..core.flags import get_flag
    timeout_s = float(get_flag("collective_timeout_s") or 0.0)
    hang = chaos.active() and chaos.probe("collective.hang")
    if timeout_s <= 0.0 and not hang:
        return fn(*args)
    if hang and timeout_s <= 0.0:
        # a hang with no watchdog budget would block the controller (the
        # faithful simulation) — useless in any harness; fail loudly at
        # the misconfiguration instead
        raise RuntimeError(
            "chaos site 'collective.hang' fired but "
            "FLAGS_collective_timeout_s is unset — set a timeout budget "
            "so the watchdog (the thing this site exists to exercise) "
            "can convert the hang into CollectiveTimeoutError")

    result: dict = {}
    done = threading.Event()

    def worker():
        try:
            if hang:
                chaos.hang_loop(max(timeout_s, 1.0) * 20 + 60.0)
            result["value"] = fn(*args)
        except BaseException as e:     # surfaces on the caller's thread
            result["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=worker, daemon=True,
                         name=f"collective-{op}")
    t.start()
    if not done.wait(timeout_s if timeout_s > 0.0 else None):
        try:
            from ..monitor import get_registry
            get_registry().counter(
                "collective_timeouts_total",
                "eager collective watchdog trips").inc(
                    op=op, group=group.axis_name)
        except Exception:
            pass
        try:
            from ..monitor import flight_recorder as _flight
            if _flight.enabled():
                _flight.get_flight_recorder().record_event(
                    "collective_timeout", op=op, group=group.axis_name,
                    nranks=group.nranks, timeout_s=timeout_s)
        except Exception:
            pass
        raise CollectiveTimeoutError(op, group, timeout_s)
    if "error" in result:
        raise result["error"]
    return result["value"]


def _check_stacked(arr, group: Group, opname: str):
    if arr.ndim == 0 or arr.shape[0] != group.nranks:
        raise ValueError(
            f"{opname}: eager collectives in the single-controller model "
            f"operate on the stacked per-rank view — expected leading axis "
            f"of size {group.nranks} (group ranks), got shape {tuple(arr.shape)}. "
            "Inside jit, call this under a shard_map with the group's axis "
            "bound (see paddle_tpu.distributed.shard_ctx).")


# ---------------------------------------------------------------------------
# Functional collectives
# ---------------------------------------------------------------------------

def all_reduce(tensor, op: int = ReduceOp.SUM, group: Optional[Group] = None,
               sync_op: bool = True, use_calc_stream: bool = False):
    """reference: collective.py:415 all_reduce → c_allreduce_{sum,max,...}."""
    g = group or _default_group()
    x = _unwrap(tensor)

    axes = _traced_axes(g)
    if axes is not None and _is_traced(x):
        if op == ReduceOp.AVG:
            out = jax.lax.pmean(x, axes if len(axes) > 1 else axes[0])
        elif op == ReduceOp.PROD:
            out = _pprod(x, axes)
        else:
            out = _LAX_REDUCE[op](x, axes if len(axes) > 1 else axes[0])
        return _rewrap(out, tensor)

    if g.nranks == 1:
        return tensor
    _check_stacked(x, g, "all_reduce")
    ax = g.axis_name

    def body(s):
        if op == ReduceOp.AVG:
            return jnp.broadcast_to(jax.lax.pmean(s, ax), s.shape)
        if op == ReduceOp.PROD:
            return jnp.broadcast_to(_pprod(s, (ax,)), s.shape)
        return jnp.broadcast_to(_LAX_REDUCE[op](s, ax), s.shape)

    with _comm_trace("all_reduce", g, x, ("all_reduce", op)):
        out = _run_collective(
            "all_reduce", g, _eager_shardmap(g, ("all_reduce", op), body), x)
    if isinstance(tensor, Tensor):
        tensor._data = out
        return tensor
    return out


def _pprod(x, axes):
    """Product reduction via all_gather (no native pprod in lax)."""
    for ax in axes:
        g = jax.lax.all_gather(x, ax)
        x = jnp.prod(g, axis=0)
    return x


def _gather_global_order(x, axes):
    """all_gather over bound axes with the result in GLOBAL RANK order.

    Gathering innermost-axis-first stacks leading dims in (outer, ..., inner)
    order; one flatten then yields row-major global ranks — matching the
    layout every eager collective and the reference guarantee."""
    out = x
    for ax in reversed(axes):
        out = jax.lax.all_gather(out, ax)
    return out.reshape((-1,) + tuple(x.shape))


def _global_axis_index(axes):
    """This shard's global rank across the bound axes (row-major)."""
    idx = None
    for ax in axes:
        i = jax.lax.axis_index(ax)
        n = jax.lax.psum(1, ax)
        idx = i if idx is None else idx * n + i
    return idx


def all_gather(tensor_or_list, tensor=None, group: Optional[Group] = None,
               sync_op: bool = True, axis: int = 0):
    """reference: collective.py:589 all_gather (fills a python list).

    Traced: returns the gathered array (leading axis = group size).
    Eager stacked: every rank slot receives the full stack.
    Called with (tensor_list, tensor) it appends per-rank tensors for parity.
    """
    g = group or _default_group()

    if tensor is None:
        x = _unwrap(tensor_or_list)
        axes = _traced_axes(g)
        if axes is not None and _is_traced(x):
            out = _gather_global_order(x, axes)
            return _rewrap(out, tensor_or_list)
        if g.nranks == 1:
            return _rewrap(jnp.expand_dims(x, 0), tensor_or_list)
        _check_stacked(x, g, "all_gather")
        ax = g.axis_name

        def body(s):
            return jax.lax.all_gather(s[0], ax)[None]

        with _comm_trace("all_gather", g, x, ("all_gather",)):
            out = _run_collective(
                "all_gather", g, _eager_shardmap(g, ("all_gather",), body),
                x)
        return _rewrap(out, tensor_or_list)

    # list-filling parity form
    tensor_list, t = tensor_or_list, tensor
    x = _unwrap(t)
    if g.nranks == 1:
        tensor_list.append(_rewrap(x, t))
        return
    _check_stacked(x, g, "all_gather")
    gathered = all_gather(x, group=g)  # [n, n, ...] per-slot stacks
    for r in range(g.nranks):
        tensor_list.append(_rewrap(gathered[0, r], t))


def all_gather_object(obj_list: List, obj, group: Optional[Group] = None):
    """Host-side object gather (reference: collective.py all_gather_object)."""
    g = group or _default_group()
    if env.get_world_size() > 1:
        from jax.experimental import multihost_utils
        import pickle
        payload = np.frombuffer(pickle.dumps(obj), np.uint8)
        # pad to max length across processes
        n = multihost_utils.process_allgather(np.array([payload.size]))
        m = int(np.max(n))
        buf = np.zeros(m, np.uint8)
        buf[:payload.size] = payload
        out = multihost_utils.process_allgather(buf)
        for i in range(out.shape[0]):
            obj_list.append(pickle.loads(out[i, :int(n[i])].tobytes()))
        return
    for _ in range(g.nranks):
        obj_list.append(obj)


def reduce(tensor, dst: int = 0, op: int = ReduceOp.SUM,
           group: Optional[Group] = None, sync_op: bool = True):
    """reference: collective.py:495 reduce → c_reduce_*; result lands on dst,
    other ranks keep their input."""
    g = group or _default_group()
    x = _unwrap(tensor)
    dst_local = g.get_group_rank(dst) if dst in g.ranks else dst

    axes = _traced_axes(g)
    if axes is not None and _is_traced(x):
        ax_arg = axes if len(axes) > 1 else axes[0]
        if op == ReduceOp.AVG:
            red = jax.lax.pmean(x, ax_arg)
        elif op == ReduceOp.PROD:
            red = _pprod(x, axes)
        else:
            red = _LAX_REDUCE[op](x, ax_arg)
        idx = _global_axis_index(axes)
        out = jnp.where(idx == dst_local, red, x)
        return _rewrap(out, tensor)

    if g.nranks == 1:
        return tensor
    _check_stacked(x, g, "reduce")
    ax = g.axis_name

    def body(s):
        if op == ReduceOp.AVG:
            red = jax.lax.pmean(s, ax)
        elif op == ReduceOp.PROD:
            red = _pprod(s, (ax,))
        else:
            red = _LAX_REDUCE[op](s, ax)
        idx = jax.lax.axis_index(ax)
        return jnp.where(idx == dst_local, red, s)

    with _comm_trace("reduce", g, x, ("reduce", op, dst_local)):
        out = _run_collective(
            "reduce", g, _eager_shardmap(g, ("reduce", op, dst_local), body),
            x)
    if isinstance(tensor, Tensor):
        tensor._data = out
        return tensor
    return out


def _group_size_traced(axes):
    return jax.lax.psum(1, axes if len(axes) > 1 else axes[0])


def broadcast(tensor, src: int = 0, group: Optional[Group] = None,
              sync_op: bool = True):
    """reference: collective.py:348 broadcast → c_broadcast."""
    g = group or _default_group()
    x = _unwrap(tensor)
    src_local = g.get_group_rank(src) if src in g.ranks else src

    axes = _traced_axes(g)
    if axes is not None and _is_traced(x):
        out = _gather_global_order(x, axes)[src_local]
        return _rewrap(out, tensor)

    if g.nranks == 1:
        return tensor
    _check_stacked(x, g, "broadcast")
    ax = g.axis_name

    def body(s):
        return jax.lax.all_gather(s[0], ax)[src_local][None]

    with _comm_trace("broadcast", g, x, ("broadcast", src_local)):
        out = _run_collective(
            "broadcast", g,
            _eager_shardmap(g, ("broadcast", src_local), body), x)
    if isinstance(tensor, Tensor):
        tensor._data = out
        return tensor
    return out


def scatter(tensor, tensor_list=None, src: int = 0,
            group: Optional[Group] = None, sync_op: bool = True):
    """reference: collective.py:666 scatter → c_scatter.

    Eager stacked form: operand is the stacked [nranks, ...] source held by
    ``src``; each rank slot receives its slice."""
    g = group or _default_group()
    if tensor_list is not None:
        stacked = jnp.stack([_unwrap(t) for t in tensor_list])
        out = scatter(stacked, src=src, group=g)
        if isinstance(tensor, Tensor):
            tensor._data = out[g.get_group_rank(env.get_rank())] \
                if out.ndim > _unwrap(tensor).ndim else out
            return tensor
        return out
    x = _unwrap(tensor)
    axes = _traced_axes(g)
    if axes is not None and _is_traced(x):
        # x: full stacked source replicated; pick this rank's slice
        idx = _global_axis_index(axes)
        out = jax.lax.dynamic_index_in_dim(x, idx, 0, keepdims=False)
        return _rewrap(out, tensor)
    if g.nranks == 1:
        return tensor
    _check_stacked(x, g, "scatter")
    # scatter of the stacked view is the identity layout-wise; each rank's
    # slot keeps row r — nothing moves (data already lives rank-major).
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group: Optional[Group] = None,
             sync_op: bool = True):
    """reference: collective.py:1395 alltoall → AllToAll; traced form lowers
    to lax.all_to_all (the MoE dispatch primitive, global_scatter_op.cc)."""
    g = group or _default_group()

    if isinstance(in_tensor_list, (list, tuple)):
        stacked = jnp.stack([_unwrap(t) for t in in_tensor_list])
        out = alltoall(stacked, group=g)
        res = [_rewrap(out[i], in_tensor_list[i]) for i in range(out.shape[0])]
        if out_tensor_list is not None:
            out_tensor_list.extend(res)
            return
        return res

    x = _unwrap(in_tensor_list)
    axes = _traced_axes(g)
    if axes is not None and _is_traced(x):
        # x: [nranks, ...] per-destination blocks on each rank
        out = jax.lax.all_to_all(x, axes[0], split_axis=0, concat_axis=0,
                                 tiled=False)
        return _rewrap(out, in_tensor_list)

    if g.nranks == 1:
        return in_tensor_list
    # eager stacked: x[r, d] = block rank r sends to rank d  (shape [n, n, ...])
    if x.ndim < 2 or x.shape[0] != g.nranks or x.shape[1] != g.nranks:
        raise ValueError(
            f"alltoall: expected stacked [nranks, nranks, ...] blocks, got "
            f"{tuple(x.shape)}")
    ax = g.axis_name

    def body(s):  # s: [1, n, ...] — this rank's outgoing blocks
        return jax.lax.all_to_all(s, ax, split_axis=1, concat_axis=0,
                                  tiled=False).swapaxes(0, 1)

    # traced under the canonical lax op name (comm::all_to_all RecordEvent
    # + comm_* registry series) — the MoE dispatch primitive's telemetry,
    # ROADMAP item 5's prerequisite for expert-parallel overlap work
    with _comm_trace("all_to_all", g, x, ("all_to_all",)):
        out = _run_collective(
            "all_to_all", g, _eager_shardmap(g, ("all_to_all",), body), x)
    return _rewrap(out, in_tensor_list)


_pending_sends: dict = {}


def send(tensor, dst: int = 0, group: Optional[Group] = None, sync_op: bool = True):
    """reference: collective.py:1472 send → send_v2 (NCCL P2P).

    Point-to-point is a *process*-level op. Single-controller SPMD has no
    second process — traced P2P over a mesh axis is :func:`ppermute_shift`
    (the pipeline-stage channel). Eagerly, send enqueues under
    (group, src=this rank, dst) and only a matching recv on the SAME process
    (i.e. dst == this rank, the self-loop the reference also permits) can
    deliver it; anything else raises instead of silently dropping."""
    g = group or _default_group()
    _pending_sends.setdefault((g.id, env.get_rank(), dst), []).append(
        _unwrap(tensor))
    return tensor


def recv(tensor, src: int = 0, group: Optional[Group] = None, sync_op: bool = True):
    """reference: collective.py:1525 recv → recv_v2."""
    g = group or _default_group()
    me = env.get_rank()
    q = _pending_sends.get((g.id, src, me))
    if q:
        val = q.pop(0)
        if not q:
            _pending_sends.pop((g.id, src, me), None)
        if isinstance(tensor, Tensor):
            tensor._data = jnp.asarray(val)
            return tensor
        return val
    raise RuntimeError(
        f"recv(src={src}): no matching send. Eager P2P only pairs within "
        "one process (send dst == recv rank); for cross-device P2P inside "
        "jit use ppermute_shift over the group's mesh axis.")


def ppermute_shift(x, group: Optional[Group] = None, shift: int = 1):
    """Ring shift: rank r's block moves to rank (r+shift)%n. The TPU-native
    send_v2/recv_v2 for pipeline stages (reference: partial_send/recv ops) —
    traced it lowers to collective-permute on ICI."""
    g = group or _default_group()
    arr = _unwrap(x)
    n = g.nranks
    axes = _traced_axes(g)
    perm = [(i, (i + shift) % n) for i in range(n)]
    if axes is not None and _is_traced(arr):
        return _rewrap(jax.lax.ppermute(arr, axes[0], perm), x)
    if n == 1:
        return x
    _check_stacked(arr, g, "ppermute_shift")
    ax = g.axis_name

    def body(s):
        return jax.lax.ppermute(s, ax, perm)

    with _comm_trace("ppermute_shift", g, arr, ("ppermute", shift)):
        out = _run_collective(
            "ppermute_shift", g,
            _eager_shardmap(g, ("ppermute", shift), body), arr)
    return _rewrap(out, x)


def barrier(group: Optional[Group] = None):
    """reference: collective.py barrier → barrier op / gloo."""
    if env.get_world_size() > 1:
        from jax.experimental import multihost_utils
        # the cross-HOST sync is the likeliest real-world hang (a dead
        # peer process): watchdog applies here too
        _run_collective(
            "barrier", group or _default_group(),
            multihost_utils.sync_global_devices, "paddle_tpu_barrier")
        return
    g = group or _default_group()
    if g.nranks > 1:
        x = jnp.zeros((g.nranks,), jnp.int32)
        out = all_reduce(x, ReduceOp.SUM, g)
        jax.block_until_ready(_unwrap(out))


def wait(tensor, group: Optional[Group] = None, use_calc_stream: bool = True):
    """reference: collective.py wait — XLA async dispatch: block on the value."""
    jax.block_until_ready(_unwrap(tensor))
    return tensor


def all_reduce_arrays(arrays: List, op: int = ReduceOp.SUM,
                      group: Optional[Group] = None) -> List:
    """Multi-process helper used by DataParallel.apply_collective_grads:
    allreduce a list of this-process arrays across processes."""
    if env.get_world_size() <= 1:
        return list(arrays)
    from jax.experimental import multihost_utils

    def gather_sum():
        out = []
        for a in arrays:
            g = multihost_utils.process_allgather(np.asarray(a))
            out.append(jnp.asarray(np.sum(g, axis=0)))
        return out

    # cross-host allgather: a dead peer hangs this forever without the
    # watchdog — the exact production scenario the timeout exists for
    return _run_collective("all_reduce_arrays", group or _default_group(),
                           gather_sum)
