"""paddle.distributed.spawn analogue.

reference parity: python/paddle/distributed/spawn.py:568 — start nprocs
worker processes running ``func(*args)`` with the trainer env protocol set,
join and re-raise the first failure (_throw_exception_if_process_failed).

Uses the 'spawn' start method so each worker gets a fresh JAX runtime
(forking a process with an initialized TPU backend is unsafe).
"""

from __future__ import annotations

import multiprocessing as mp
import os
from typing import Optional, Sequence

__all__ = ["spawn", "SpawnContext"]


def _worker(func, args, rank: int, nprocs: int, master: str, port: int):
    os.environ.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nprocs),
        "PADDLE_MASTER": master,
        "MASTER_ADDR": master,
        "MASTER_PORT": str(port),
        "PADDLE_LOCAL_RANK": str(rank),
    })
    func(*args)


class SpawnContext:
    def __init__(self, procs):
        self.processes = procs

    def join(self, timeout: Optional[float] = None) -> bool:
        for p in self.processes:
            p.join(timeout)
        failed = [p for p in self.processes if p.exitcode not in (0, None)]
        if failed:
            codes = {p.pid: p.exitcode for p in failed}
            for p in self.processes:        # stop stragglers, fail fast
                if p.is_alive():
                    p.terminate()
            raise RuntimeError(f"spawned workers failed: {codes}")
        return all(p.exitcode == 0 for p in self.processes)


def spawn(func, args: Sequence = (), nprocs: int = 1, join: bool = True,
          master: str = "127.0.0.1", port: int = 12355, **options):
    """Run ``func`` in nprocs fresh processes with the trainer env set."""
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_worker,
                        args=(func, tuple(args), rank, nprocs, master, port))
        p.daemon = options.get("daemon", False)
        p.start()
        procs.append(p)
    context = SpawnContext(procs)
    if join:
        context.join()
    return context
