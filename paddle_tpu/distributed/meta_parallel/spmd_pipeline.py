"""SPMD pipeline parallelism: mesh-placed stages in ONE jitted program.

reference parity: fleet/meta_parallel/pipeline_parallel.py:80-151 (1F1B
schedule, one process per stage), pp_utils/p2p_communication.py:25-443
(NCCL p2p activation send/recv), framework/section_worker.cc:153 (per-stage
worker threads).

TPU-native redesign — collective-permute pipelining (the GSPMD/scaling-book
formulation) instead of a process-per-stage runtime:

- The pipeline body is N identical blocks whose parameters are STACKED
  along a leading layer axis ([L, ...] per leaf) and sharded over the
  ``pp`` mesh axis, so stage s physically owns layers
  [s*L/S, (s+1)*L/S) — the analogue of the reference's per-stage
  parameter placement, expressed as a layout. Inside each stage the local
  layers run as one ``jax.lax.scan`` (the nn/scan.py scan-over-layers
  recipe), so trace/compile cost is O(1) in depth.
- TWO schedules share that layout (selected by ``fleet.strategy``'s
  ``pipeline_configs['schedule_mode']`` / ``FLAGS_pipeline_schedule``;
  see :func:`resolve_schedule`):

  ``fill_drain`` (GPipe) — one ``lax.scan`` over T = M + S - 1 ticks
  advances every stage in lockstep inside a partial-manual ``shard_map``
  (manual over ``pp``, auto/GSPMD over dp/mp/sp — tensor parallelism
  keeps working inside each stage). Each tick ``lax.ppermute`` rotates
  activations stage -> stage+1 over ICI. Backward is plain ``jax.grad``
  through the scan (ppermute transposes to the reverse rotation), with
  ``jax.checkpoint`` on the stage body. This is the kill-switch fallback:
  forward-only execution (eval, logits) always uses it.

  ``1f1b`` — the real one-forward-one-backward schedule as ONE combined
  fwd+bwd program (:meth:`PipelineStageStack.train_loss`). A scan over
  T = 2(M + S - 1) slots; at slot t, stage s runs the FORWARD of
  microbatch m_f = (t - s)/2 when t ≡ s (mod 2) and the BACKWARD of
  m_b = (t - (2S-1-s))/2 on the opposite parity (``lax.switch`` on a
  per-device predicate — real branch divergence, not masking). The loss
  head runs on the LAST stage inside its forward slot, so each
  microbatch's backward starts one slot after its forward finishes —
  the canonical 1F1B timetable: bubble (S-1)/(M+S-1), in-flight
  activations bounded by S - s stage INPUTS per stage (a ring buffer;
  backward recomputes the stage from its saved input — activation
  memory O(S), not O(M)). The O(S) bound is for INTER-LAYER
  activations; the microbatched model input x_mb and its gradient
  buffer are O(B) on every rank (replicated in-spec + dx carry used
  only where s == 0) — both schedules pay that, it is the price of
  returning dx for the embedding backward at this interface. Both ppermutes (activations down, cotangents
  up) issue every slot OUTSIDE the branch so XLA's async scheduler can
  overlap them with the slot's compute; parameter gradients accumulate
  per stage and the DP reduction of the accumulated grads is left to
  GSPMD, which schedules it against the backward tail.

  The 1F1B program computes loss AND gradients in its forward pass and
  exposes them through ``jax.custom_vjp`` whose backward merely scales
  by the incoming loss cotangent — exact for any LINEAR consumer of the
  loss (sums, means, AMP loss scaling), which is every trainer here.

Numerical parity: both schedules only reorder *which device* computes a
microbatch — parity with sequential execution is exact up to float
reassociation of the per-microbatch loss sums (pinned in
tests/test_pipeline_1f1b.py at 1e-6). Stochastic models: both schedules
derive stage RNG from the same (microbatch, stage) fold, so dropout
masks are schedule-invariant and the kill switch preserves trajectories
for dropout > 0 too (pinned); the NON-pipelined sequential path keys
per layer over the whole batch instead of per microbatch, so dropout>0
parity holds between schedules but not vs single-device execution.

Backend capability: XLA:CPU's SPMD partitioner cannot compile
manual-subgroup collectives (a ``ppermute``/``psum`` inside a shard_map
that is manual over ``pp`` but auto over a NONTRIVIAL dp/mp axis
hard-aborts the process: ``Check failed: IsManualSubgroup``; plain
``axis_index`` raises ``PartitionId ... not supported``). TPU is fine.
:func:`manual_collectives_ok` gates every pipelined program; unsupported
meshes degrade to sequential GSPMD execution of the SAME pp-sharded
stacked parameters (bit-identical math, no schedule) with a one-time
warning + ``pipeline_fallback_total`` counter, mirroring nn/scan.py's
fallback telemetry.

Fault tolerance: eager dispatches of pipeline programs run under the PR 5
collective watchdog (``FLAGS_collective_timeout_s`` + chaos site
``collective.hang``), so a hung stage handoff raises a structured
:class:`~paddle_tpu.distributed.collective.CollectiveTimeoutError`
instead of stalling the controller; TrainStep applies the same guard to
its whole step program when the model contains a pipeline (see
jit/to_static.py).
"""

from __future__ import annotations

import warnings
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ...core.flags import get_flag
from ...core.random import make_rng, trace_rng
from ...core.tensor import Tensor, apply
from ...nn.layer import Layer
from .. import env as dist_env

__all__ = ["PP_AXIS", "PipelineStageStack", "resolve_schedule",
           "manual_collectives_ok", "bubble_fraction", "schedule_slots",
           "schedule_timetable", "pipeline_comm_model", "PIPELINE_STATS",
           "reset_pipeline_stats", "note_pipeline_fallback"]

PP_AXIS = "pp"

SCHEDULES = ("fill_drain", "1f1b")

#: observability (the nn/scan.py SCAN_STATS convention): programs built,
#: eager dispatches, and schedule fallbacks (pp mesh present but the
#: pipelined program could not run — backend capability or config).
PIPELINE_STATS = {"programs_built": 0, "dispatches": 0, "fallbacks": 0}

_FALLBACK_WARNED: set = set()


def reset_pipeline_stats():
    PIPELINE_STATS["programs_built"] = 0
    PIPELINE_STATS["dispatches"] = 0
    PIPELINE_STATS["fallbacks"] = 0
    _FALLBACK_WARNED.clear()


def note_pipeline_fallback(reason: str, detail: str = "") -> None:
    """A pp>1 mesh is active but the pipelined program degraded to
    sequential GSPMD execution — make the silent-degradation loud
    (one-time RuntimeWarning per reason) and countable."""
    PIPELINE_STATS["fallbacks"] += 1
    key = (reason, detail)
    if key not in _FALLBACK_WARNED:
        _FALLBACK_WARNED.add(key)
        warnings.warn(
            f"SPMD pipeline degraded to sequential execution (reason: "
            f"{reason}{'; ' + detail if detail else ''}); the math is "
            "unchanged but no pipeline schedule runs. On XLA:CPU this is "
            "expected for meshes with nontrivial dp/mp axes (the SPMD "
            "partitioner cannot compile manual-subgroup collectives); on "
            "TPU check FLAGS_pipeline_schedule and the mesh axes.",
            RuntimeWarning, stacklevel=3)
    from ...monitor import enabled as _mon_enabled
    if _mon_enabled():
        from ...monitor import get_registry
        get_registry().counter(
            "pipeline_fallback_total",
            "pp meshes that degraded to sequential execution, by cause",
        ).inc(reason=reason)


def manual_collectives_ok(mesh, axis: str = PP_AXIS) -> bool:
    """Can this backend compile collectives inside a shard_map manual over
    ``axis`` with the other mesh axes auto?

    XLA:CPU (jax 0.4.37): NO when any other axis has size > 1 — the SPMD
    partitioner aborts on manual-subgroup collectives (``Check failed:
    IsManualSubgroup``), and even reaching it requires surviving the
    ``PartitionId`` lowering of axis_index. Trivial auto axes partition
    to a no-op, so pp-only meshes work everywhere. TPU/GPU: yes.
    """
    if mesh is None or axis not in mesh.axis_names:
        return False
    if jax.default_backend() != "cpu":
        return True
    return all(int(mesh.shape[a]) == 1
               for a in mesh.axis_names if a != axis)


def resolve_schedule(explicit: Optional[str] = None) -> str:
    """Pipeline schedule resolution: ``FLAGS_pipeline_schedule`` (global
    kill switch) > explicit constructor/config arg > the fleet strategy's
    ``pipeline_configs['schedule_mode']`` (reference spelling ``1F1B`` /
    ``F-then-B`` normalized) > ``1f1b`` default."""
    flag = str(get_flag("pipeline_schedule") or "").strip()
    for cand in (flag, explicit or ""):
        norm = _normalize_schedule(cand)
        if norm:
            return norm
    try:
        from ..fleet import _strategy
        mode = _strategy().pipeline_configs.get("schedule_mode", "1F1B")
    except Exception:
        mode = "1F1B"
    return _normalize_schedule(str(mode)) or "1f1b"


def _normalize_schedule(name: str) -> Optional[str]:
    s = name.strip().lower().replace("-", "_")
    if not s:
        return None
    if s in ("1f1b", "one_f_one_b"):
        return "1f1b"
    if s in ("fill_drain", "f_then_b", "fthenb", "gpipe"):
        return "fill_drain"
    raise ValueError(
        f"unknown pipeline schedule {name!r}; expected one of "
        f"{SCHEDULES} (FLAGS_pipeline_schedule / "
        "strategy.pipeline_configs['schedule_mode'])")


def schedule_slots(schedule: str, S: int, M: int) -> int:
    """Lockstep slots the schedule occupies. fill_drain counts forward
    ticks only (backward is the autodiff mirror, same count); 1f1b counts
    combined fwd+bwd slots."""
    if S <= 1:
        return M
    return (M + S - 1) if schedule == "fill_drain" else 2 * (M + S - 1)


def bubble_fraction(schedule: str, S: int, M: int) -> float:
    """Idle-slot fraction of the schedule. Both fill_drain (fwd scan +
    its autodiff mirror) and 1f1b sit at the canonical
    (S-1)/(M+S-1) — 1f1b's win over fill_drain is the O(S) activation
    memory, not the bubble."""
    if S <= 1:
        return 0.0
    return (S - 1) / (M + S - 1)


def schedule_timetable(schedule: str, S: int, M: int) -> Dict[str, np.ndarray]:
    """Host-side occupancy grid of the IMPLEMENTED schedule predicates.

    Returns ``{"fwd": [S, T], "bwd": [S, T], "busy": [S, T],
    "bubble_fraction": float}`` where ``fwd[s, t]`` is True iff stage s
    does useful forward work in slot t. For ``1f1b`` this replays the
    exact integer predicates the traced program branches on
    (``f_valid``/``b_valid`` in :meth:`PipelineStageStack._1f1b_fn`), so
    the bubble here is *measured from the implementation's timetable*,
    not the closed-form formula — bench/tests gate the two against each
    other. ``fill_drain`` models the forward scan plus its autodiff
    mirror (same occupancy, time-reversed)."""
    T = schedule_slots(schedule, S, M)
    s = np.arange(S)[:, None]
    t = np.arange(T)[None, :]
    if S <= 1:
        fwd = np.ones((S, T), bool)
        bwd = np.zeros((S, T), bool)
    elif schedule == "fill_drain":
        # forward tick t runs microbatch t - s on stage s when valid; the
        # backward mirror has identical occupancy reversed in time
        fwd = (t - s >= 0) & (t - s < M)
        bwd = fwd[:, ::-1]
    else:
        m_f = (t - s) // 2
        f_par = (t - s) % 2 == 0
        fwd = f_par & (m_f >= 0) & (m_f < M)
        m_b = (t - (2 * S - 1 - s)) // 2
        bwd = (~f_par) & (m_b >= 0) & (m_b < M)
    if schedule == "fill_drain" and S > 1:
        # fwd scan and bwd mirror are two sequential passes of T ticks
        busy = np.concatenate([fwd, bwd], axis=1)
    else:
        busy = fwd | bwd
    frac = 1.0 - float(busy.sum()) / busy.size if busy.size else 0.0
    return {"fwd": fwd, "bwd": bwd, "busy": busy,
            "bubble_fraction": frac}


def pipeline_comm_model(schedule: str, S: int, M: int,
                        boundary_bytes: int) -> Dict[str, float]:
    """Static per-step comm model of the schedule's stage handoffs:
    ppermute ops and bytes moved per optimizer step (per device).
    fill_drain: one activation permute per forward tick + its transpose
    per backward tick; 1f1b: one activation + one cotangent permute per
    slot. ``boundary_bytes`` = bytes of ONE microbatch's stage-boundary
    activation."""
    if S <= 1:
        return {"ops": 0, "bytes": 0, "slots": schedule_slots(
            schedule, S, M), "bubble_fraction": 0.0}
    slots = schedule_slots(schedule, S, M)
    # one permute pair per slot either way: 1f1b sends activation +
    # cotangent every slot; fill_drain sends one activation per forward
    # tick plus its transpose in the backward mirror
    ops = 2 * slots
    return {"ops": float(ops), "bytes": float(ops) * boundary_bytes,
            "slots": float(slots),
            "bubble_fraction": bubble_fraction(schedule, S, M)}


def _reg_name(template_name: str) -> str:
    """Dotted template param path -> attribute-safe registration name."""
    return "stacked__" + template_name.replace(".", "__")


def _pp_group(S: int):
    """Lightweight Group handle naming the pp axis for watchdog/telemetry
    labels (no ring bootstrap — the axis name IS the communicator)."""
    from ..collective import Group
    return Group(list(range(S)), gid=-101, axis_name=PP_AXIS)


def _guarded_dispatch(op: str, S: int, fn, *args):
    """Eager pipeline-program dispatch under the PR 5 collective watchdog
    (FLAGS_collective_timeout_s / chaos ``collective.hang``): a hung stage
    handoff becomes a structured CollectiveTimeoutError. Traced calls
    (inside an outer jit) bypass — the enclosing TrainStep guards its own
    dispatch."""
    if any(isinstance(a, jax.core.Tracer)
           for a in jax.tree_util.tree_leaves(args)):
        return fn(*args)
    PIPELINE_STATS["dispatches"] += 1
    from ..collective import _run_collective
    return _run_collective(op, _pp_group(S), fn, *args)


class PipelineStageStack(Layer):
    """N structurally-identical blocks stacked into [L, ...] parameters and
    executed as an SPMD pipeline over the ``pp`` mesh axis.

    ``layer_factory() -> Layer`` is called once per layer for
    initialization (each draws its own init RNG) and once more for the
    *template* whose forward() is traced per stage. Blocks must map an
    input of shape X to an output of the same shape (residual blocks) and
    must not own buffers.

    Without a mesh (or with pp degree 1) the stack degrades to sequential
    execution of the same stacked parameters — bit-identical math, no
    pipeline machinery, so one model definition serves 1..S stages. The
    same degradation applies (with a warning + counter) on backends that
    cannot compile the pipelined program (see
    :func:`manual_collectives_ok`).

    ``schedule`` picks the training schedule for :meth:`train_loss`
    (``None`` = resolve from FLAGS/fleet strategy at call time);
    :meth:`forward` (logits/eval) always runs the fill-drain forward.
    """

    def __init__(self, layer_factory: Callable[[], Layer], num_layers: int,
                 axis: str = PP_AXIS,
                 num_microbatches: Optional[int] = None, remat: bool = True,
                 schedule: Optional[str] = None):
        super().__init__()
        self.axis = axis
        self.num_layers = int(num_layers)
        self.num_microbatches = num_microbatches
        self.remat = remat
        if schedule is not None:
            _normalize_schedule(schedule)       # validate eagerly
        self.schedule = schedule

        template = layer_factory()
        if dict(template.named_buffers()):
            raise ValueError(
                "PipelineStageStack blocks must not own buffers (got "
                f"{list(dict(template.named_buffers()))}); fold running "
                "stats out of the pipelined body")
        # the template is a tracing vehicle, not a child module: its params
        # are placeholders that bind() swaps for stacked slices
        self.__dict__["_template"] = template

        # stack per-layer initializations: [L, ...] leaves
        per_layer = [dict((k, p._data) for k, p in
                          template.named_parameters())]
        for _ in range(self.num_layers - 1):
            blk = layer_factory()
            per_layer.append({k: p._data
                              for k, p in blk.named_parameters()})

        self._name_map: Dict[str, str] = {}
        t_params = dict(template.named_parameters())
        for tname, tparam in t_params.items():
            stacked = jnp.stack([d[tname] for d in per_layer])
            rname = _reg_name(tname)
            self._name_map[rname] = tname
            param = self.create_parameter(
                stacked.shape, dtype=str(stacked.dtype),
                default_initializer=lambda shape, dtype, _a=stacked: _a)
            tspec = getattr(tparam, "spec", None) or P()
            param.spec = P(self.axis, *tuple(tspec))
            setattr(self, rname, param)

    # -- degree bookkeeping ------------------------------------------------
    def _pp_degree(self) -> int:
        mesh = dist_env.get_mesh()
        if mesh is not None and self.axis in mesh.axis_names:
            return int(mesh.shape[self.axis])
        return 1

    def resolved_schedule(self) -> str:
        return resolve_schedule(self.schedule)

    def _sync_template_mode(self):
        tmpl = self.__dict__["_template"]
        tmpl.training = self.training
        for sub in tmpl.sublayers():
            sub.training = self.training

    def _stage_apply(self, local_params, h, key):
        """Run this stage's L/S layers over raw arrays (template-bound).

        Composes the nn/scan.py scan-over-layers recipe inside the stage:
        the local layer slice runs as ONE ``jax.lax.scan`` (trace cost
        O(1) in local depth, each layer folding its index into the stage
        RNG key) — the ``FLAGS_scan_layers`` kill switch restores the
        per-layer Python loop."""
        from ...jit.functional import bind
        tmpl = self.__dict__["_template"]
        n_local = int(local_params[next(iter(local_params))].shape[0])
        if not get_flag("scan_layers") or n_local < 2:
            with trace_rng(key):
                for j in range(n_local):
                    sl = {k: v[j] for k, v in local_params.items()}
                    with bind(tmpl, sl):
                        h = tmpl(Tensor(h))._data
            return h

        from ...nn.scan import SCAN_STATS
        SCAN_STATS["scan_calls"] += 1

        def body(carry, xs):
            SCAN_STATS["body_traces"] += 1
            sl, j = xs
            with trace_rng(jax.random.fold_in(key, j)), bind(tmpl, sl):
                out = tmpl(Tensor(carry))._data
            return out.astype(carry.dtype), None

        h_out, _ = jax.lax.scan(
            body, h,
            (dict(local_params), jnp.arange(n_local, dtype=jnp.int32)))
        return h_out

    def _can_pipeline(self, S: int, note: bool = True) -> bool:
        """pp > 1 AND the backend can compile the manual-pp program.
        ``note=False`` probes without counting — train_loss's schedule
        pick probes first and then delegates to forward(), whose own
        check records the ONE fallback for the degraded dispatch."""
        if S <= 1:
            return False
        mesh = dist_env.get_mesh()
        if not manual_collectives_ok(mesh, self.axis):
            if note:
                note_pipeline_fallback(
                    "manual_collectives_unsupported",
                    f"backend={jax.default_backend()} mesh="
                    f"{dict(mesh.shape) if mesh is not None else None}")
            return False
        return True

    def _resolve_M(self, num_microbatches: Optional[int], S: int,
                   B: int) -> int:
        M = int(num_microbatches or self.num_microbatches or S)
        if B % M:
            raise ValueError(f"batch {B} not divisible into {M} "
                             "microbatches")
        return M

    # -- execution ---------------------------------------------------------
    def forward(self, x, num_microbatches: Optional[int] = None):
        self._sync_template_mode()
        S = self._pp_degree()
        rnames = list(self._name_map)
        params = [getattr(self, r) for r in rnames]

        if not self._can_pipeline(S):
            def seq_fn(h, *leaves):
                local = {self._name_map[r]: a
                         for r, a in zip(rnames, leaves)}
                return self._stage_apply(local, h, make_rng("pipeline"))
            return apply(seq_fn, x, *params, name="pipeline_seq")

        if self.num_layers % S:
            raise ValueError(f"pp degree {S} must divide num_layers "
                             f"{self.num_layers}")
        M = self._resolve_M(num_microbatches, S, x.shape[0])
        mesh = dist_env.get_mesh()
        mb = x.shape[0] // M
        pipe = self._pipe_program(mesh, S, M, mb)

        def pipe_fn(x_raw, *leaves):
            x_mb = x_raw.reshape((M, mb) + x_raw.shape[1:])
            out_mb = _guarded_dispatch(
                "pipeline.fill_drain", S, pipe, x_mb,
                make_rng("pipeline"), *leaves)
            return out_mb.reshape((x_raw.shape[0],) + out_mb.shape[2:])

        return apply(pipe_fn, x, *params, name="spmd_pipeline")

    def _pipe_program(self, mesh, S: int, M: int, mb: int):
        """Cached jitted shard_map fill-drain program for (mesh, S, M, mb,
        training). The jax.jit object must persist across forward() calls
        or every eager call would recompile; it inlines when tracing."""
        cache = self.__dict__.setdefault("_pipe_cache", {})
        ckey = (id(mesh), "fill_drain", S, M, mb, self.training, self.remat)
        cached = cache.get(ckey)
        if cached is not None:
            return cached

        axis = self.axis
        rnames = list(self._name_map)
        T = M + S - 1
        stage = self._stage_apply
        if self.remat:
            stage = jax.checkpoint(stage, static_argnums=())

        def shard_body(xs, key, *local_leaves):
            local = {self._name_map[r]: a
                     for r, a in zip(rnames, local_leaves)}

            def tick(carry, t):
                idx = jax.lax.axis_index(axis)
                x_sel = jax.lax.dynamic_index_in_dim(
                    xs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
                h = jnp.where(idx == 0, x_sel, carry)
                # stage RNG keyed by (microbatch, stage) — the SAME fold
                # the 1F1B program uses (stage_key in _1f1b_fn), so
                # dropout masks are schedule-invariant and the
                # FLAGS_pipeline_schedule kill switch stays 1e-6-parity
                # even for stochastic models. At tick t this stage works
                # on microbatch t - idx (clipped on fill/drain garbage
                # ticks, whose outputs are discarded).
                m = jnp.clip(t - idx, 0, M - 1)
                tkey = jax.random.fold_in(jax.random.fold_in(key, m), idx)
                y = stage(local, h, tkey)
                nxt = jax.lax.ppermute(
                    y, axis, [(i, i + 1) for i in range(S - 1)])
                return nxt, y

            _, ys = jax.lax.scan(tick, jnp.zeros_like(xs[0]),
                                 jnp.arange(T))
            # valid outputs live on the last stage at ticks S-1..T-1
            out = ys[S - 1:]
            idx = jax.lax.axis_index(axis)
            return jax.lax.psum(
                jnp.where(idx == S - 1, out, jnp.zeros([], out.dtype)),
                axis)

        # partial-manual shard_map (manual pp, auto dp/mp/sp) is only
        # legal under jit; jax.jit inlines when we are already inside an
        # outer trace and compiles (once, cached) for eager calls
        pipe = jax.jit(dist_env.shard_map(
            shard_body, mesh=mesh,
            in_specs=(P(), P()) + (P(axis),) * len(rnames),
            out_specs=P(), axis_names={axis}, check_vma=False))
        cache[ckey] = pipe
        PIPELINE_STATS["programs_built"] += 1
        self._publish_comm_model("fill_drain", S, M)
        return pipe

    # -- schedule-aware training loss --------------------------------------
    def train_loss(self, x, head_apply: Callable, head_leaves: Sequence,
                   mb_args: Sequence = (),
                   num_microbatches: Optional[int] = None,
                   head_token=None):
        """Pipelined training loss under the resolved schedule.

        ``head_apply(head_leaf_arrays, y, *mb_arg_arrays) ->
        (loss_sum, denom)``: the loss head applied AFTER the stack — raw
        jax arrays in, two f32 scalars out (sum of per-token losses and
        the normalizer, e.g. the loss-mask sum). The same function serves
        every schedule (on the last stage, per microbatch, under 1f1b; on
        the full batch under fill_drain/sequential), so the math is
        identical up to summation order. Returns the scalar loss Tensor
        ``loss_sum / max(denom, 1)``.

        ``head_leaves``/``mb_args`` are Tensors: head parameters (receive
        gradients) and per-sample data (labels/masks, split into
        microbatches along dim 0 for 1f1b; no cotangents — data).
        ``head_token``: hashable identity for ``head_apply`` so cached
        traces survive across calls (pass something stable).

        Schedule selection: :func:`resolve_schedule`; 1f1b additionally
        requires training mode, pp > 1 and a capable backend, otherwise
        it falls back to fill_drain (counted when the cause is backend
        capability).
        """
        self._sync_template_mode()
        S = self._pp_degree()
        sched = self.resolved_schedule()
        use_1f1b = (sched == "1f1b" and self.training
                    and self._can_pipeline(S, note=False))
        n_mb = len(mb_args)

        if not use_1f1b:
            out = self.forward(x, num_microbatches=num_microbatches)

            def head_fn(y, *rest):
                return head_apply(list(rest[n_mb:]), y, *rest[:n_mb])

            ls, dn = apply(head_fn, out, *mb_args, *head_leaves,
                           name="pipeline_head",
                           _cache_token=("pipe_head", head_token, n_mb,
                                         self.training))
            return apply(lambda a, b: a / jnp.maximum(b, 1.0), ls, dn,
                         name="pipeline_loss")

        if self.num_layers % S:
            raise ValueError(f"pp degree {S} must divide num_layers "
                             f"{self.num_layers}")
        M = self._resolve_M(num_microbatches, S, x.shape[0])
        mesh = dist_env.get_mesh()
        mb = x.shape[0] // M
        rnames = list(self._name_map)
        params = [getattr(self, r) for r in rnames]
        n_stack = len(params)
        fn = self._1f1b_fn(mesh, S, M, head_apply, n_mb, n_stack,
                           len(head_leaves), head_token)

        def big(x_raw, *rest):
            x_mb = x_raw.reshape((M, mb) + x_raw.shape[1:])
            from ..spmd import constrain
            x_mb = constrain(x_mb, None, "__batch__")
            key = make_rng("pipeline")
            key = key._data if isinstance(key, Tensor) else key
            # typed keys cannot cross custom_vjp (no tangent type): ship
            # the raw uint32 key data, rewrap inside the program
            if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
                key = jax.random.key_data(key)
            mb_raw = tuple(
                a.reshape((M, mb) + a.shape[1:]) for a in rest[:n_mb])
            sid = jnp.arange(S, dtype=jnp.int32)
            return fn(x_mb, key, sid, *mb_raw, *rest[n_mb:])

        ls, dn = apply(big, x, *mb_args, *params, *head_leaves,
                       name="spmd_pipeline_1f1b",
                       _cache_token=("pipe_1f1b", id(mesh), S, M, mb,
                                     head_token, n_mb, self.training))
        return apply(lambda a, b: a / jnp.maximum(b, 1.0), ls, dn,
                     name="pipeline_loss")

    def _1f1b_fn(self, mesh, S: int, M: int, head_apply, n_mb: int,
                 n_stack: int, n_head: int, head_token):
        """Build (and cache) the custom_vjp 1F1B combined program.

        Signature of the returned fn (all positional):
            (x_mb [M,mb,...], key_data, sid [S], *mb_args [M,mb,...],
             *stack_leaves [L,...], *head_leaves) -> (loss_sum, denom)
        """
        cache = self.__dict__.setdefault("_pipe_cache", {})
        ckey = (id(mesh), "1f1b", S, M, self.training, head_token, n_mb,
                n_stack, n_head)
        cached = cache.get(ckey)
        if cached is not None:
            return cached

        axis = self.axis
        rnames = list(self._name_map)
        tnames = [self._name_map[r] for r in rnames]
        T = 2 * (M + S - 1)
        stage = self._stage_apply

        def program(x_mb, kd, sid, *rest):
            mb_raw = rest[:n_mb]
            stack_loc = {t: a for t, a in zip(tnames, rest[n_mb:n_mb +
                                                           n_stack])}
            head_raw = list(rest[n_mb + n_stack:])
            key = jax.random.wrap_key_data(kd)
            s = sid[0]

            def stage_key(m):
                return jax.random.fold_in(jax.random.fold_in(key, m), s)

            zero_h = jnp.zeros_like(x_mb[0])
            zero_head = [jnp.zeros_like(a) for a in head_raw]
            zero_stack = {t: jnp.zeros_like(a)
                          for t, a in stack_loc.items()}

            def slot(carry, sigma):
                (h_recv, g_recv, g_self, fbuf, dxbuf, gacc, hacc,
                 loss_sum, denom) = carry
                m_f = (sigma - s) // 2
                f_par = (sigma - s) % 2 == 0
                f_valid = f_par & (m_f >= 0) & (m_f < M)
                m_b = (sigma - (2 * S - 1 - s)) // 2
                b_valid = (~f_par) & (m_b >= 0) & (m_b < M)
                m_f_c = jnp.clip(m_f, 0, M - 1)
                m_b_c = jnp.clip(m_b, 0, M - 1)

                x_sel = jax.lax.dynamic_index_in_dim(
                    x_mb, m_f_c, 0, keepdims=False)
                h_in = jnp.where(s == 0, x_sel, h_recv)
                mb_f = tuple(jax.lax.dynamic_index_in_dim(
                    a, m_f_c, 0, keepdims=False) for a in mb_raw)

                def f_branch(_):
                    y = stage(stack_loc, h_in, stage_key(m_f_c))

                    def do_head(_):
                        (ls, dn), vjp = jax.vjp(
                            lambda hl, yy: head_apply(hl, yy, *mb_f),
                            head_raw, y)
                        dhead, dy = vjp((jnp.float32(1.0),
                                         jnp.float32(0.0)))
                        return dy, dhead, ls, dn

                    def no_head(_):
                        return (jnp.zeros_like(y), zero_head,
                                jnp.float32(0.0), jnp.float32(0.0))

                    dy, dhead, ls, dn = jax.lax.cond(
                        s == S - 1, do_head, no_head, None)
                    new_fbuf = jax.lax.dynamic_update_index_in_dim(
                        fbuf, h_in, m_f_c % S, 0)
                    return dict(y_send=y, g_send=zero_h, g_self=dy,
                                fbuf=new_fbuf, dxbuf=dxbuf,
                                dstack=zero_stack, dhead=dhead, ls=ls,
                                dn=dn)

                def b_branch(_):
                    h_saved = jax.lax.dynamic_index_in_dim(
                        fbuf, m_b_c % S, 0, keepdims=False)
                    g_in = jnp.where(s == S - 1, g_self, g_recv)
                    _, vjp = jax.vjp(
                        lambda p, h: stage(p, h, stage_key(m_b_c)),
                        stack_loc, h_saved)
                    dstack, dh = vjp(g_in.astype(h_saved.dtype)
                                     if g_in.dtype != h_saved.dtype
                                     else g_in)
                    new_dx = jnp.where(
                        s == 0,
                        jax.lax.dynamic_update_index_in_dim(
                            dxbuf, dh.astype(dxbuf.dtype), m_b_c, 0),
                        dxbuf)
                    return dict(y_send=zero_h, g_send=dh, g_self=g_self,
                                fbuf=fbuf, dxbuf=new_dx, dstack=dstack,
                                dhead=zero_head, ls=jnp.float32(0.0),
                                dn=jnp.float32(0.0))

                def idle(_):
                    return dict(y_send=zero_h, g_send=zero_h,
                                g_self=g_self, fbuf=fbuf, dxbuf=dxbuf,
                                dstack=zero_stack, dhead=zero_head,
                                ls=jnp.float32(0.0), dn=jnp.float32(0.0))

                branch = jnp.where(f_valid, 0, jnp.where(b_valid, 1, 2))
                o = jax.lax.switch(branch, [f_branch, b_branch, idle],
                                   None)
                # stage handoffs OUTSIDE the branch, both directions each
                # slot — double-buffered into the carry (sent this slot,
                # consumed next slot) so XLA can overlap the permutes with
                # the slot's compute
                h_next = jax.lax.ppermute(
                    o["y_send"], axis, [(i, i + 1) for i in range(S - 1)])
                g_next = jax.lax.ppermute(
                    o["g_send"], axis, [(i + 1, i) for i in range(S - 1)])
                gacc2 = {t: gacc[t] + o["dstack"][t] for t in gacc}
                hacc2 = [a + d for a, d in zip(hacc, o["dhead"])]
                return ((h_next, g_next, o["g_self"], o["fbuf"],
                         o["dxbuf"], gacc2, hacc2, loss_sum + o["ls"],
                         denom + o["dn"]), None)

            carry0 = (zero_h, zero_h, zero_h,
                      jnp.zeros((S,) + x_mb.shape[1:], x_mb.dtype),
                      jnp.zeros_like(x_mb), zero_stack, zero_head,
                      jnp.float32(0.0), jnp.float32(0.0))
            carry, _ = jax.lax.scan(slot, carry0,
                                    jnp.arange(T, dtype=jnp.int32))
            (_, _, _, _, dxbuf, gacc, hacc, loss_sum, denom) = carry
            last = s == S - 1
            loss_sum = jax.lax.psum(jnp.where(last, loss_sum, 0.0), axis)
            denom = jax.lax.psum(jnp.where(last, denom, 0.0), axis)
            dx = jax.lax.psum(
                jnp.where(s == 0, dxbuf, jnp.zeros_like(dxbuf)), axis)
            hgrads = [jax.lax.psum(a, axis) for a in hacc]
            return (loss_sum, denom, dx,
                    tuple(gacc[t] for t in tnames), tuple(hgrads))

        in_specs = ((P(), P(), P(axis)) + (P(),) * n_mb
                    + (P(axis),) * n_stack + (P(),) * n_head)
        out_specs = (P(), P(), P(), (P(axis),) * n_stack, (P(),) * n_head)
        pipe = jax.jit(dist_env.shard_map(
            program, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            axis_names={axis}, check_vma=False))

        def run(*args):
            return _guarded_dispatch("pipeline.1f1b", S, pipe, *args)

        @jax.custom_vjp
        def f(*args):
            ls, dn, _, _, _ = run(*args)
            return ls, dn

        def f_fwd(*args):
            ls, dn, dx, gstack, ghead = run(*args)
            # keep the non-diff args so bwd can shape their zero/float0
            # cotangents (labels/masks/key/sid are data, not parameters)
            return (ls, dn), (dx, gstack, ghead, args[1], args[2],
                              args[3:3 + n_mb])

        def f_bwd(res, g):
            dx, gstack, ghead, kd, sid, mb_raw = res
            g_ls, _g_dn = g

            def data_cot(a):
                if jnp.issubdtype(a.dtype, jnp.floating):
                    return jnp.zeros_like(a)
                return np.zeros(a.shape, dtype=jax.dtypes.float0)

            return ((dx * g_ls, data_cot(kd), data_cot(sid))
                    + tuple(data_cot(a) for a in mb_raw)
                    + tuple(gl * g_ls for gl in gstack)
                    + tuple(gh * g_ls for gh in ghead))

        f.defvjp(f_fwd, f_bwd)

        cache[ckey] = f
        PIPELINE_STATS["programs_built"] += 1
        self._publish_comm_model("1f1b", S, M)
        return f

    # -- observability ----------------------------------------------------
    def _publish_comm_model(self, schedule: str, S: int, M: int) -> None:
        """Registry gauges describing the schedule's comm structure (the
        traced collectives the eager comm_* series cannot see):
        per-step ppermute ops/bytes and the analytic bubble fraction.
        tools/monitor_report.py --comms renders them next to the eager
        collectives table. Monitor off = zero registry writes."""
        try:
            from ...monitor import enabled as _mon_enabled
            if not _mon_enabled():
                return
            from ...monitor import get_registry
            reg = get_registry()
            labels = {"op": "ppermute", "schedule": schedule, "pp": S,
                      "microbatches": M}
            model = pipeline_comm_model(schedule, S, M, 0)
            reg.gauge(
                "pipeline_comm_ops_per_step",
                "traced stage-handoff collectives per optimizer step "
                "(schedule model)").set(model["ops"], **labels)
            reg.gauge(
                "pipeline_bubble_fraction",
                "analytic schedule bubble (idle-slot share)").set(
                    model["bubble_fraction"], **labels)
        except Exception:
            pass

    # -- interop -----------------------------------------------------------
    def layer_state_dict(self, i: int) -> Dict[str, jax.Array]:
        """Per-layer view of the stacked parameters (template names)."""
        return {self._name_map[r]: getattr(self, r)._data[i]
                for r in self._name_map}

    def load_from_layers(self, layers):
        """Restack parameters from a list of per-layer Layers (e.g. a
        non-pipelined model's blocks) — resume/convert path."""
        if len(layers) != self.num_layers:
            raise ValueError("layer count mismatch")
        dicts = [{k: p._data for k, p in l.named_parameters()}
                 for l in layers]
        for rname, tname in self._name_map.items():
            getattr(self, rname)._data = jnp.stack(
                [d[tname] for d in dicts])
