"""SPMD pipeline parallelism: mesh-placed stages in ONE jitted program.

reference parity: fleet/meta_parallel/pipeline_parallel.py:80-151 (1F1B
schedule, one process per stage), pp_utils/p2p_communication.py:25-443
(NCCL p2p activation send/recv), framework/section_worker.cc:153 (per-stage
worker threads).

TPU-native redesign — collective-permute pipelining (the GSPMD/scaling-book
formulation) instead of a process-per-stage runtime:

- The pipeline body is N identical blocks whose parameters are STACKED
  along a leading layer axis ([L, ...] per leaf) and sharded over the
  ``pp`` mesh axis, so stage s physically owns layers
  [s*L/S, (s+1)*L/S) — the analogue of the reference's per-stage
  parameter placement, expressed as a layout.
- One ``lax.scan`` over T = M + S - 1 ticks advances every stage in
  lockstep inside a partial-manual ``shard_map`` (manual over ``pp``,
  auto/GSPMD over dp/mp/sp — tensor parallelism keeps working inside each
  stage). Each tick, ``lax.ppermute`` rotates activations
  stage -> stage+1 over ICI: the send/recv pair of
  p2p_communication.py as a single XLA collective.
- Backward is plain ``jax.grad`` through the scan (ppermute transposes to
  the reverse rotation — recv_backward/send_backward for free), with
  ``jax.checkpoint`` on the stage body so in-flight activation memory is
  O(M) stage-boundary activations rather than O(M * L/S) layer
  internals — the same memory bound 1F1B exists to provide. Fill-drain
  (GPipe) + remat is the schedule that maps to a single SPMD program; the
  bubble fraction (S-1)/(T) matches 1F1B and shrinks with more
  microbatches.

Numerical parity with sequential execution is exact (the schedule only
reorders *which device* computes a microbatch, not the math).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...core.random import make_rng, trace_rng
from ...core.tensor import Tensor, apply
from ...nn.layer import Layer
from .. import env as dist_env

__all__ = ["PP_AXIS", "PipelineStageStack"]

PP_AXIS = "pp"


def _reg_name(template_name: str) -> str:
    """Dotted template param path -> attribute-safe registration name."""
    return "stacked__" + template_name.replace(".", "__")


class PipelineStageStack(Layer):
    """N structurally-identical blocks stacked into [L, ...] parameters and
    executed as an SPMD pipeline over the ``pp`` mesh axis.

    ``layer_factory() -> Layer`` is called once per layer for
    initialization (each draws its own init RNG) and once more for the
    *template* whose forward() is traced per stage. Blocks must map an
    input of shape X to an output of the same shape (residual blocks) and
    must not own buffers.

    Without a mesh (or with pp degree 1) the stack degrades to sequential
    execution of the same stacked parameters — bit-identical math, no
    pipeline machinery, so one model definition serves 1..S stages.
    """

    def __init__(self, layer_factory: Callable[[], Layer], num_layers: int,
                 axis: str = PP_AXIS,
                 num_microbatches: Optional[int] = None, remat: bool = True):
        super().__init__()
        self.axis = axis
        self.num_layers = int(num_layers)
        self.num_microbatches = num_microbatches
        self.remat = remat

        template = layer_factory()
        if dict(template.named_buffers()):
            raise ValueError(
                "PipelineStageStack blocks must not own buffers (got "
                f"{list(dict(template.named_buffers()))}); fold running "
                "stats out of the pipelined body")
        # the template is a tracing vehicle, not a child module: its params
        # are placeholders that bind() swaps for stacked slices
        self.__dict__["_template"] = template

        # stack per-layer initializations: [L, ...] leaves
        per_layer = [dict((k, p._data) for k, p in
                          template.named_parameters())]
        for _ in range(self.num_layers - 1):
            blk = layer_factory()
            per_layer.append({k: p._data
                              for k, p in blk.named_parameters()})

        self._name_map: Dict[str, str] = {}
        t_params = dict(template.named_parameters())
        for tname, tparam in t_params.items():
            stacked = jnp.stack([d[tname] for d in per_layer])
            rname = _reg_name(tname)
            self._name_map[rname] = tname
            param = self.create_parameter(
                stacked.shape, dtype=str(stacked.dtype),
                default_initializer=lambda shape, dtype, _a=stacked: _a)
            tspec = getattr(tparam, "spec", None) or P()
            param.spec = P(self.axis, *tuple(tspec))
            setattr(self, rname, param)

    # -- degree bookkeeping ------------------------------------------------
    def _pp_degree(self) -> int:
        mesh = dist_env.get_mesh()
        if mesh is not None and self.axis in mesh.axis_names:
            return int(mesh.shape[self.axis])
        return 1

    def _sync_template_mode(self):
        tmpl = self.__dict__["_template"]
        tmpl.training = self.training
        for sub in tmpl.sublayers():
            sub.training = self.training

    def _stage_apply(self, local_params, h, key):
        """Run this stage's L/S layers over raw arrays (template-bound)."""
        from ...jit.functional import bind
        tmpl = self.__dict__["_template"]
        n_local = local_params[next(iter(local_params))].shape[0]
        with trace_rng(key):
            for j in range(n_local):
                sl = {k: v[j] for k, v in local_params.items()}
                with bind(tmpl, sl):
                    h = tmpl(Tensor(h))._data
        return h

    # -- execution ---------------------------------------------------------
    def forward(self, x, num_microbatches: Optional[int] = None):
        self._sync_template_mode()
        S = self._pp_degree()
        rnames = list(self._name_map)
        params = [getattr(self, r) for r in rnames]

        if S == 1:
            def seq_fn(h, *leaves):
                local = {self._name_map[r]: a
                         for r, a in zip(rnames, leaves)}
                return self._stage_apply(local, h, make_rng("pipeline"))
            return apply(seq_fn, x, *params, name="pipeline_seq")

        if self.num_layers % S:
            raise ValueError(f"pp degree {S} must divide num_layers "
                             f"{self.num_layers}")
        M = int(num_microbatches or self.num_microbatches or S)
        B = x.shape[0]
        if B % M:
            raise ValueError(f"batch {B} not divisible into {M} "
                             "microbatches")
        mesh = dist_env.get_mesh()
        mb = B // M
        pipe = self._pipe_program(mesh, S, M, mb)

        def pipe_fn(x_raw, *leaves):
            x_mb = x_raw.reshape((M, mb) + x_raw.shape[1:])
            out_mb = pipe(x_mb, make_rng("pipeline"), *leaves)
            return out_mb.reshape((B,) + out_mb.shape[2:])

        return apply(pipe_fn, x, *params, name="spmd_pipeline")

    def _pipe_program(self, mesh, S: int, M: int, mb: int):
        """Cached jitted shard_map pipeline program for (mesh, S, M, mb,
        training). The jax.jit object must persist across forward() calls
        or every eager call would recompile; it inlines when tracing."""
        cache = self.__dict__.setdefault("_pipe_cache", {})
        ckey = (id(mesh), S, M, mb, self.training, self.remat)
        cached = cache.get(ckey)
        if cached is not None:
            return cached

        axis = self.axis
        rnames = list(self._name_map)
        T = M + S - 1
        stage = self._stage_apply
        if self.remat:
            stage = jax.checkpoint(stage, static_argnums=())

        def shard_body(xs, key, *local_leaves):
            local = {self._name_map[r]: a
                     for r, a in zip(rnames, local_leaves)}

            def tick(carry, t):
                idx = jax.lax.axis_index(axis)
                x_sel = jax.lax.dynamic_index_in_dim(
                    xs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
                h = jnp.where(idx == 0, x_sel, carry)
                tkey = jax.random.fold_in(jax.random.fold_in(key, t), idx)
                y = stage(local, h, tkey)
                nxt = jax.lax.ppermute(
                    y, axis, [(i, i + 1) for i in range(S - 1)])
                return nxt, y

            _, ys = jax.lax.scan(tick, jnp.zeros_like(xs[0]),
                                 jnp.arange(T))
            # valid outputs live on the last stage at ticks S-1..T-1
            out = ys[S - 1:]
            idx = jax.lax.axis_index(axis)
            return jax.lax.psum(
                jnp.where(idx == S - 1, out, jnp.zeros([], out.dtype)),
                axis)

        # partial-manual shard_map (manual pp, auto dp/mp/sp) is only
        # legal under jit; jax.jit inlines when we are already inside an
        # outer trace and compiles (once, cached) for eager calls
        pipe = jax.jit(dist_env.shard_map(
            shard_body, mesh=mesh,
            in_specs=(P(), P()) + (P(axis),) * len(rnames),
            out_specs=P(), axis_names={axis}, check_vma=False))
        cache[ckey] = pipe
        return pipe

    # -- interop -----------------------------------------------------------
    def layer_state_dict(self, i: int) -> Dict[str, jax.Array]:
        """Per-layer view of the stacked parameters (template names)."""
        return {self._name_map[r]: getattr(self, r)._data[i]
                for r in self._name_map}

    def load_from_layers(self, layers):
        """Restack parameters from a list of per-layer Layers (e.g. a
        non-pipelined model's blocks) — resume/convert path."""
        if len(layers) != self.num_layers:
            raise ValueError("layer count mismatch")
        dicts = [{k: p._data for k, p in l.named_parameters()}
                 for l in layers]
        for rname, tname in self._name_map.items():
            getattr(self, rname)._data = jnp.stack(
                [d[tname] for d in dicts])
