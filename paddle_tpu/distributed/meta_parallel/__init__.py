"""meta_parallel: hybrid-parallel model engines.

reference: python/paddle/distributed/fleet/meta_parallel/ —
TensorParallel (tensor_parallel.py:25), PipelineParallel
(pipeline_parallel.py:80, 1F1B), ShardingParallel, and parallel_layers/
(mp_layers.py TP building blocks, pp_layers.py PipelineLayer, random.py
RNG tracker).

TPU-native: engines don't rewrite graphs or drive NCCL — they attach
PartitionSpecs and wrap the train step in one SPMD jit over the fleet mesh.
"""

from .parallel_base import ShardingParallel, TensorParallel  # noqa: F401


def __getattr__(name):
    import importlib
    if name in ("parallel_layers",):
        return importlib.import_module(f".{name}", __name__)
    if name in ("PipelineParallel", "PipelineLayer", "LayerDesc",
                "SharedLayerDesc"):
        mod = importlib.import_module(".pipeline_parallel", __name__)
        return getattr(mod, name)
    if name in ("VocabParallelEmbedding", "ColumnParallelLinear",
                "RowParallelLinear", "ParallelCrossEntropy"):
        mod = importlib.import_module(".parallel_layers.mp_layers", __name__)
        return getattr(mod, name)
    raise AttributeError(
        f"module 'paddle_tpu.distributed.meta_parallel' has no attribute {name!r}")
