"""TensorParallel / ShardingParallel engine wrappers.

reference: fleet/meta_parallel/tensor_parallel.py:25 (broadcast params +
grads sync across mp group) and sharding_parallel.py. In the SPMD design
parameter placement is declarative: the wrapper stamps each Parameter's
PartitionSpec (``Parameter.spec``) and the jitted TrainStep lays arrays out
with `jax.device_put`; XLA inserts the collectives — no broadcast/Reducer.
"""

from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ...nn.layer import Layer


class _MetaParallelBase(Layer):
    def __init__(self, layers: Layer, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        self._prepare()

    def _prepare(self):
        pass

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)


class TensorParallel(_MetaParallelBase):
    """Marks mp-sharded params; everything else is replicated.

    The mp_layers (ColumnParallelLinear etc.) stamp their own specs at
    construction; this engine fills in `spec=None → replicated` and is the
    place grad-clip norm reduction over the mp group is attached.
    """

    def _prepare(self):
        for _, p in self._layers.named_parameters():
            if getattr(p, "spec", None) is None:
                p.spec = P()  # replicated


class ShardingParallel(_MetaParallelBase):
    """ZeRO-style: optimizer state sharded over the 'sharding' axis; param
    specs stay replicated (stage 1/2). The actual opt-state PartitionSpecs
    are applied by TrainStep (jit/to_static.py) reading hcg."""

    def _prepare(self):
        for _, p in self._layers.named_parameters():
            if getattr(p, "spec", None) is None:
                p.spec = P()
