"""Pipeline layer descriptions + stage segmentation.

reference parity: fleet/meta_parallel/parallel_layers/pp_layers.py —
LayerDesc(:31), SharedLayerDesc(:49), SegmentLayers(:63 uniform/param-count
balancing), PipelineLayer(:132 builds only the local stage's layers).

TPU-native difference: a single SPMD controller owns every stage, so
PipelineLayer materializes ALL stages (each stage is an nn.Sequential) and
the schedule (pipeline_parallel.py) walks them; placement over the 'pp'
mesh axis is a layout concern, not a process-identity concern.
"""

from __future__ import annotations

import re
from typing import Callable, List, Optional, Sequence

from ....nn.layer import Layer, Sequential

__all__ = ["LayerDesc", "SharedLayerDesc", "SegmentLayers", "PipelineLayer"]


class LayerDesc:
    """Deferred layer construction: class + ctor args, built per stage."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer) and not callable(layer_func):
            raise TypeError("LayerDesc expects a Layer subclass or callable")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        name = getattr(self.layer_func, "__name__", str(self.layer_func))
        return f"LayerDesc({name})"


class SharedLayerDesc(LayerDesc):
    """A layer whose parameters are shared across stages (e.g. tied
    embedding/LM-head). All descs with the same ``key`` resolve to ONE
    built layer instance; ``forward_func`` customizes the call at reuse
    sites (reference: pp_layers.py:49)."""

    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class _SharedCall(Layer):
    """Call-site wrapper around a shared layer instance."""

    def __init__(self, shared: Layer, forward_func: Optional[Callable]):
        super().__init__()
        # register as sublayer only at the FIRST site; later sites hold a
        # plain reference so parameters are not double-counted
        object.__setattr__(self, "_shared_ref", shared)
        self._forward_func = forward_func

    def forward(self, *args, **kwargs):
        if self._forward_func is not None:
            return self._forward_func(self._shared_ref, *args, **kwargs)
        return self._shared_ref(*args, **kwargs)


class SegmentLayers:
    """Split a desc list into num_parts contiguous segments.

    method="uniform": equal layer counts. method="layer:<Name>": one
    boundary before each layer whose class name matches, mirroring the
    reference's seg_method="layer:TransformerBlock" style.
    """

    def __init__(self, layers_desc: Sequence, num_parts: int,
                 method: str = "uniform"):
        self.descs = list(layers_desc)
        self.num_parts = int(num_parts)
        self.method = method
        if len(self.descs) < self.num_parts:
            raise ValueError(
                f"{len(self.descs)} layers cannot fill {num_parts} stages")

    def do_segment(self) -> List[int]:
        n, parts = len(self.descs), self.num_parts
        if self.method == "uniform":
            base, rem = divmod(n, parts)
            bounds = [0]
            for i in range(parts):
                bounds.append(bounds[-1] + base + (1 if i < rem else 0))
            return bounds
        m = re.match(r"layer:(.+)", self.method)
        if m:
            name = m.group(1)
            marks = [i for i, d in enumerate(self.descs)
                     if self._desc_name(d) == name]
            if len(marks) < parts:
                raise ValueError(
                    f"only {len(marks)} '{name}' layers for {parts} stages")
            # distribute the matched layers evenly; boundary = first matched
            # layer of each chunk
            bounds = [0]
            base, rem = divmod(len(marks), parts)
            idx = 0
            for i in range(parts - 1):
                idx += base + (1 if i < rem else 0)
                bounds.append(marks[idx])
            bounds.append(n)
            return bounds
        raise ValueError(f"unknown seg method {self.method!r}")

    @staticmethod
    def _desc_name(d) -> str:
        if isinstance(d, LayerDesc):
            return getattr(d.layer_func, "__name__", "")
        return type(d).__name__


class PipelineLayer(Layer):
    """The whole network as an ordered desc list, segmented into stages.

    Unlike the reference (which builds only the stage owned by this
    process, pp_layers.py:132), every stage is materialized — the SPMD
    controller drives all of them; `stage(i)` returns stage i's Sequential.
    """

    def __init__(self, layers: Sequence, num_stages: int = 1,
                 loss_fn=None, seg_method: str = "uniform", topology=None,
                 recompute_interval: int = 0):
        super().__init__()
        self._descs = list(layers)
        self._num_stages = int(num_stages)
        self._loss_fn = loss_fn
        self._topology = topology
        self._recompute_interval = recompute_interval
        self._shared_instances = {}
        self.segment_bounds = SegmentLayers(
            self._descs, self._num_stages, seg_method).do_segment()
        self._stages = []
        for s in range(self._num_stages):
            lo, hi = self.segment_bounds[s], self.segment_bounds[s + 1]
            built = [self._build(d) for d in self._descs[lo:hi]]
            stage = Sequential(*built)
            self._stages.append(stage)
            self.add_sublayer(f"stage_{s}", stage)

    def _build(self, desc):
        if isinstance(desc, SharedLayerDesc):
            key = desc.layer_name
            if key not in self._shared_instances:
                inst = desc.build_layer()
                self._shared_instances[key] = inst
                wrapper = _SharedCall(inst, desc.forward_func)
                # first site owns the params
                wrapper.add_sublayer("shared", inst)
                return wrapper
            return _SharedCall(self._shared_instances[key],
                               desc.forward_func)
        if isinstance(desc, LayerDesc):
            built = desc.build_layer()
        elif isinstance(desc, Layer):
            built = desc
        elif callable(desc):
            built = _FnLayer(desc)
        else:
            raise TypeError(f"cannot build pipeline layer from {desc!r}")
        if self._recompute_interval:
            from ...fleet.utils import recompute

            class _Recomputed(Layer):
                def __init__(self, inner):
                    super().__init__()
                    self.inner = inner

                def forward(self, *a, **kw):
                    return recompute(self.inner, *a, **kw)
            return _Recomputed(built)
        return built

    @property
    def num_stages(self) -> int:
        return self._num_stages

    def stage(self, i: int) -> Sequential:
        return self._stages[i]

    def shared_layer(self, key: str) -> Layer:
        return self._shared_instances[key]

    def forward(self, x):
        for s in self._stages:
            x = s(x)
        return x

    def loss(self, output, labels):
        if self._loss_fn is None:
            raise ValueError("PipelineLayer built without loss_fn")
        return self._loss_fn(output, labels)


class _FnLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, *args, **kwargs):
        return self._fn(*args, **kwargs)
