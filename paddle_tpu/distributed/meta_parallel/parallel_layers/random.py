"""TP-safe RNG stream tracker.

reference: fleet/meta_parallel/parallel_layers/random.py:32 RNGStatesTracker —
tracks named CUDA RNG states so dropout inside model-parallel regions uses a
per-rank ('local_seed') stream while regions outside use a cross-rank
identical ('global_seed') stream.

TPU-native: streams are fold-in offsets on the trace key
(core/random.py), so the tracker is a thin façade that registers offsets and
scopes a stream name.
"""

from __future__ import annotations

import contextlib

from ....core import random as _random

MODEL_PARALLEL_RNG = "local_seed"
GLOBAL_RNG = "global_seed"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}

    def add(self, name: str, seed: int):
        if name in self.states_:
            raise ValueError(f"state {name!r} already exists")
        self.states_[name] = int(seed)
        _random.register_rng_stream(name, int(seed))

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)
        for name, seed in self.states_.items():
            _random.register_rng_stream(name, int(seed))

    @contextlib.contextmanager
    def rng_state(self, name: str = MODEL_PARALLEL_RNG):
        """Scope subsequent make_rng draws to the named stream."""
        with _random.stream_scope(name):
            yield


_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _tracker


def model_parallel_random_seed(seed: int = 0):
    """reference: random.py:86 — derive per-rank local + shared global
    streams from one base seed."""
    from ...fleet import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    mp_rank = hcg.get_model_parallel_rank() if hcg is not None else 0
    global _tracker
    _tracker = RNGStatesTracker()
    _tracker.add(GLOBAL_RNG, seed)
    _tracker.add(MODEL_PARALLEL_RNG, seed + 1024 + mp_rank)
    _random.seed(seed)
