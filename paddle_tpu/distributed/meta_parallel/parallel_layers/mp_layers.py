"""Tensor-parallel layers: vocab/column/row-sharded modules.

reference: fleet/meta_parallel/parallel_layers/mp_layers.py —
VocabParallelEmbedding(:30) masks out-of-shard ids, looks up the local
vocab slice and c_allreduce_sums; ColumnParallelLinear(:97) holds the
out-dim shard with optional c_concat gather; RowParallelLinear(:170) holds
the in-dim shard and c_allreduce_sums partial products;
ParallelCrossEntropy(:249) is the vocab-parallel softmax CE
(c_softmax_with_cross_entropy_op.cu).

TPU-native (GSPMD): layers hold the FULL logical parameter annotated with a
`PartitionSpec` (`Parameter.spec`); under jit over a mesh the arrays are
laid out by those specs and XLA's SPMD partitioner inserts the very same
collectives the reference writes by hand (masked gather + psum for the
embedding, psum for row-parallel matmul). `with_sharding_constraint` pins
the activation layouts (gather_output / input_is_parallel semantics).
Eagerly on one device the layers behave as their dense equivalents.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ....nn import functional as F
from ....nn.initializer import Normal, XavierUniform
from ....nn.layer import Layer
from ... import env

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy", "split"]

MP_AXIS = "mp"


def _mesh():
    m = env.get_mesh()
    if m is not None and MP_AXIS in m.axis_names:
        return m
    return None


from ...spmd import constrain as _constrain  # shared layout-pin helper


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over the mp axis."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 weight_attr=None, mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=Normal(0.0, 0.02))
        self.weight.spec = P(MP_AXIS, None)
        self.weight.is_distributed = True

    def forward(self, x):
        out = F.embedding(x, self.weight)
        # output replicated over mp (the psum the reference writes by hand)
        return _constrain(out, *([None] * len(out.shape)))


class ColumnParallelLinear(Layer):
    """Linear with the OUT dim sharded (weight [in, out~mp])."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, gather_output: bool = True,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight.spec = P(None, MP_AXIS)
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias.spec = P(MP_AXIS)
            self.bias.is_distributed = True
        else:
            self.bias = None

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return _constrain(y, *([None] * len(y.shape)))
        # keep the activation sharded on its last dim (reference: no c_concat)
        return _constrain(y, *([None] * (len(y.shape) - 1) + [MP_AXIS]))


class RowParallelLinear(Layer):
    """Linear with the IN dim sharded (weight [in~mp, out]); partial products
    are summed over mp — GSPMD inserts the psum the reference's
    c_allreduce_sum does."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, input_is_parallel: bool = False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight.spec = P(MP_AXIS, None)
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias.spec = P()  # replicated — added after the psum
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            x = _constrain(x, *([None] * (len(x.shape) - 1) + [MP_AXIS]))
        y = F.linear(x, self.weight, None)
        y = _constrain(y, *([None] * len(y.shape)))
        if self.bias is not None:
            y = y + self.bias
        return y


class ParallelCrossEntropy(Layer):
    """Vocab-parallel softmax cross entropy.

    reference: mp_layers.py:249 → c_softmax_with_cross_entropy_op.cu — the
    max/sum reductions run across the vocab-sharded axis. Here the stable
    composition's reductions are partitioned by GSPMD (logits arrive sharded
    [..., V~mp] from a gather_output=False column layer).

    Single chip (no mp mesh): large vocabularies stream over chunks with an
    online f32 logsumexp (nn/chunked_ce.py) instead of materializing the
    full-vocab f32 log-probs — the dense mp-sharded composition is kept
    whenever an mp mesh is active, since GSPMD partitions its reductions
    across the vocab shards (chunk slicing would fight that layout)."""

    def __init__(self, mp_group=None, name=None):
        super().__init__()

    def forward(self, logits, label):
        from ....core.tensor import apply
        import jax.numpy as jnp
        from ....nn import chunked_ce as _cce

        vocab = logits.shape[-1]
        use_chunked = _mesh() is None and _cce.enabled_for(vocab)
        chunk = _cce.chunk_size_for(vocab) if use_chunked else 0

        def _ce(lg, lab):
            ids = lab.astype(jnp.int32)
            if ids.ndim == lg.ndim:
                ids = jnp.squeeze(ids, -1)
            if use_chunked:
                return _cce.hard_nll(lg, ids, chunk=chunk)[..., None]
            lg32 = lg.astype(jnp.float32)
            m = jnp.max(lg32, axis=-1, keepdims=True)
            z = lg32 - jax.lax.stop_gradient(m)
            lse = jnp.log(jnp.sum(jnp.exp(z), axis=-1))
            tgt = jnp.take_along_axis(z, ids[..., None], axis=-1)[..., 0]
            return (lse - tgt)[..., None]

        # hard_nll's Pallas-vs-XLA dispatch resolves at trace time — the
        # outcome rides the cache token so a kill-switch flip can never
        # serve a stale cached trace (same rule as F.cross_entropy)
        from ....ops import pallas as pallas_ops
        ce_kernel = (use_chunked
                     and pallas_ops.kernel_enabled("chunked_ce",
                                                   note=False))
        return apply(_ce, logits, label, name="parallel_cross_entropy",
                     _cache_token=("parallel_ce", use_chunked, chunk,
                                   ce_kernel))


def split(x, size, operation: str, axis: int = 0, gather_out: bool = True,
          weight_attr=None, bias_attr=None, name=None):
    """reference: collective.py:1233 paddle.distributed.split — build a
    sharded linear/embedding layer in one call."""
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1],
                                       weight_attr=weight_attr)
        return layer(x)
    if operation == "linear":
        if axis == 0:
            layer = RowParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                      has_bias=bias_attr is not False)
        else:
            layer = ColumnParallelLinear(size[0], size[1],
                                         weight_attr=weight_attr,
                                         has_bias=bias_attr is not False,
                                         gather_output=gather_out)
        return layer(x)
    raise ValueError(f"unknown split operation {operation!r}")
