"""parallel_layers: TP building blocks + pipeline containers + RNG tracker.

reference: python/paddle/distributed/fleet/meta_parallel/parallel_layers/
(mp_layers.py, pp_layers.py, random.py).
"""

from .mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    split,
)
from .random import RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed  # noqa: F401
