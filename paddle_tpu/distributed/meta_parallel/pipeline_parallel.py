"""Pipeline-parallel engine: microbatched 1F1B schedule.

reference parity: fleet/meta_parallel/pipeline_parallel.py —
PipelineParallel(:30), forward_backward_pipeline(:80) with
startup/steady/cooldown phases (1F1B), train_batch(:152), p2p activation
send/recv (p2p_communication.py).

TPU-native redesign: the reference runs one process per stage and moves
activations with NCCL p2p. Here one SPMD controller owns every stage:
the schedule is a host-side loop over jit-compiled stage functions, and
"send/recv" is an on-device array handoff (XLA keeps arrays resident; on a
multi-stage mesh the transfer rides ICI via device_put). The 1F1B order is
preserved exactly — warmup forwards, steady 1F1B pairs, cooldown
backwards — because it bounds in-flight activation memory to
pipeline_depth, which matters identically on TPU HBM.

Gradient flow between stages uses the eager tape: each microbatch segment
keeps its VJP closure; `backward(grad_tensor)` returns the activation
gradient to pass upstream (the analogue of send_backward/recv_backward).
"""

from __future__ import annotations

from typing import List, Optional

from ...core.tensor import Tensor
from .parallel_base import _MetaParallelBase
from .parallel_layers.pp_layers import PipelineLayer

__all__ = ["PipelineParallel"]


class PipelineParallel(_MetaParallelBase):
    def __init__(self, layers: PipelineLayer, hcg=None, strategy=None,
                 accumulate_steps: Optional[int] = None):
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel needs a PipelineLayer")
        super().__init__(layers, hcg, strategy)
        cfg = getattr(strategy, "pipeline_configs", None) or {}
        self.accumulate_steps = int(
            accumulate_steps if accumulate_steps is not None
            else cfg.get("accumulate_steps", 1))
        self.num_stages = layers.num_stages
        # schedule log for tests/inspection: ("F"|"B", stage, microbatch)
        self._schedule_log: List[tuple] = []

    # -- helpers -----------------------------------------------------------
    def _split_micro(self, data):
        """Split [B, ...] batch tensors into accumulate_steps microbatches."""
        inputs, labels = data
        n = self.accumulate_steps

        def split(t):
            t = t if isinstance(t, Tensor) else Tensor(t)
            B = t.shape[0]
            if B % n:
                raise ValueError(f"batch {B} not divisible into {n} "
                                 "microbatches")
            m = B // n
            return [t[i * m:(i + 1) * m] for i in range(n)]
        return split(inputs), split(labels)

    def _fwd_stage(self, s: int, x: Tensor, mb: int) -> Tensor:
        self._schedule_log.append(("F", s, mb))
        return self._layers.stage(s)(x)

    def _bwd_stage(self, out: Tensor, grad: Optional[Tensor], mb: int,
                   s: int) -> None:
        self._schedule_log.append(("B", s, mb))
        out.backward(grad_tensor=grad)

    # -- 1F1B --------------------------------------------------------------
    def forward_backward_pipeline(self, data, scaler=None):
        """One full microbatched fwd+bwd pass; grads accumulate into
        Parameter.grad. Returns the mean loss over microbatches.

        Schedule (per reference pipeline_parallel.py:80): with S stages and
        M microbatches, warmup = S-1 forwards on early microbatches, then
        steady-state 1F1B pairs, then cooldown backwards. In-flight
        activations never exceed S microbatches.
        """
        self._schedule_log.clear()
        micro_in, micro_lab = self._split_micro(data)
        M, S = self.accumulate_steps, self.num_stages

        losses = {}            # scaled losses (backward roots)
        report = {}            # UNSCALED values for the returned loss
        inputs = [[None] * S for _ in range(M)]    # stage input leaves
        outputs = [[None] * S for _ in range(M)]   # stage output tensors

        def run_forward(mb):
            x = micro_in[mb]
            for s in range(S):
                if s > 0:
                    # detach = the send/recv boundary: the tape segments per
                    # stage, each stage backwards independently
                    x = x.detach()
                    x.stop_gradient = False
                inputs[mb][s] = x
                x = self._fwd_stage(s, x, mb)
                outputs[mb][s] = x
            loss = self._layers.loss(x, micro_lab[mb]) / M
            report[mb] = loss.detach()
            if scaler is not None:
                loss = scaler.scale(loss)
            losses[mb] = loss

        def run_backward(mb):
            self._bwd_stage(losses[mb], None, mb, S - 1)
            for s in range(S - 2, -1, -1):
                grad = inputs[mb][s + 1].grad
                self._bwd_stage(outputs[mb][s], grad, mb, s)
            inputs[mb] = [None] * S                # free activations
            outputs[mb] = [None] * S

        warmup = min(S - 1, M)
        steady = M - warmup

        for mb in range(warmup):
            run_forward(mb)
        for i in range(steady):
            run_forward(warmup + i)
            run_backward(i)
        for mb in range(steady, M):
            run_backward(mb)

        total = float(report[0]) if M else 0.0
        for mb in range(1, M):
            total += float(report[mb])
        import jax.numpy as jnp
        return Tensor(jnp.asarray(total, jnp.float32))

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Microbatched train step: 1F1B fwd/bwd + ONE optimizer step.
        reference: pipeline_parallel.py:152."""
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        micro_in, micro_lab = self._split_micro(data)
        from ...core.tensor import no_grad
        outs = []
        with no_grad():
            for mb in range(self.accumulate_steps):
                x = micro_in[mb]
                for s in range(self.num_stages):
                    x = self._fwd_stage(s, x, mb)
                if compute_loss:
                    outs.append(self._layers.loss(x, micro_lab[mb])
                                / self.accumulate_steps)
                else:
                    outs.append(x)
        if compute_loss:
            total = outs[0]
            for l in outs[1:]:
                total = total + l
            return total
        return outs
