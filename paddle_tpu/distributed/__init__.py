"""paddle_tpu.distributed — mesh-based parallelism over XLA collectives.

Reference surface: python/paddle/distributed/ (collective.py, fleet/,
parallel.py, spawn.py, launch). Design mapping (see SURVEY.md §5/§7):
ring_id→named mesh axes, c_allreduce→psum, send/recv→ppermute,
meta-optimizer program rewrites→sharding specs + function transforms.
"""

from . import env  # noqa: F401
from .parallel import (  # noqa: F401
    DataParallel,
    ParallelEnv,
    get_rank,
    get_world_size,
    init_parallel_env,
)


def __getattr__(name):
    # lazy imports to avoid heavy costs / cycles at package import
    if name in ("all_reduce", "all_gather", "broadcast", "reduce", "scatter",
                "alltoall", "send", "recv", "barrier", "new_group", "wait",
                "ReduceOp", "split", "all_reduce_arrays"):
        from . import collective
        return getattr(collective, name)
    if name == "fleet":
        from . import fleet
        return fleet
    if name == "meta_parallel":
        from . import meta_parallel
        return meta_parallel
    if name == "spawn":
        from .spawn_mod import spawn
        return spawn
    if name == "launch":
        from . import launch
        return launch
    raise AttributeError(f"module 'paddle_tpu.distributed' has no attribute {name!r}")
