"""paddle_tpu.distributed — mesh-based parallelism over XLA collectives.

Reference surface: python/paddle/distributed/ (collective.py, fleet/,
parallel.py, spawn.py, launch). Design mapping (see SURVEY.md §5/§7):
ring_id→named mesh axes, c_allreduce→psum, send/recv→ppermute,
meta-optimizer program rewrites→sharding specs + function transforms.
"""

from . import env  # noqa: F401
from . import collective  # noqa: F401
from . import spmd  # noqa: F401
from . import checkpoint  # noqa: F401
from .collective import (  # noqa: F401
    Group,
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    all_reduce_arrays,
    alltoall,
    barrier,
    broadcast,
    destroy_process_group,
    get_group,
    new_group,
    ppermute_shift,
    recv,
    reduce,
    scatter,
    send,
    wait,
)
from .parallel import (  # noqa: F401
    DataParallel,
    ParallelEnv,
    get_rank,
    get_world_size,
    init_parallel_env,
)
from .spmd import apply_param_shardings, make_mesh, shard_map  # noqa: F401


def __getattr__(name):
    # submodules imported lazily (they pull in engines/launchers)
    import importlib
    if name in ("fleet", "meta_parallel", "launch"):
        return importlib.import_module(f".{name}", __name__)
    if name == "spawn":
        return importlib.import_module(".spawn_mod", __name__).spawn
    if name == "split":
        from .meta_parallel.parallel_layers.mp_layers import split
        return split
    raise AttributeError(
        f"module 'paddle_tpu.distributed' has no attribute {name!r}")
