"""init_parallel_env + DataParallel.

Reference: python/paddle/distributed/parallel.py:69 (init_parallel_env boots
NCCL+gloo per rank) and fluid/dygraph/parallel.py:389 (DataParallel wraps the
model with a C++ Reducer doing bucketed grad allreduce).

TPU-native: `init_parallel_env` calls jax.distributed.initialize (the
coordination service replaces TCP NCCL-id exchange) and records the default
device mesh. `DataParallel` needs NO reducer — inside a jitted step, grads of
a data-sharded batch are averaged by a single psum that XLA schedules to
overlap with the backward (the compiler replaces the Reducer's bucketing
heuristics). Eagerly (single-host) it runs the layer unchanged and provides
grad-allreduce hooks for multi-process parity tests.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from ..nn.layer import Layer
from . import env

__all__ = ["init_parallel_env", "get_rank", "get_world_size", "DataParallel",
           "ParallelEnv"]


def init_parallel_env():
    """Boot multi-process JAX if env vars are present; no-op single-process."""
    if env.is_initialized():
        return ParallelEnv()
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR")
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if coord and nprocs > 1:
        port = os.environ.get("MASTER_PORT", "12355")
        jax.distributed.initialize(
            coordinator_address=f"{coord}:{port}",
            num_processes=nprocs,
            process_id=rank,
        )
    env.mark_initialized()
    return ParallelEnv()


def get_rank() -> int:
    return env.get_rank()


def get_world_size() -> int:
    return env.get_world_size()


class ParallelEnv:
    """reference: fluid/dygraph/parallel.py ParallelEnv."""

    @property
    def rank(self):
        return env.get_rank()

    @property
    def world_size(self):
        return env.get_world_size()

    @property
    def device_id(self):
        return int(os.environ.get("FLAGS_selected_tpus", "0").split(",")[0])

    local_rank = rank
    nranks = world_size


class DataParallel(Layer):
    """Wraps a layer for data-parallel training.

    In the jitted path, `paddle_tpu.distributed.fleet.distributed_model`
    shards the batch over the mesh 'dp' axis and XLA inserts the grad
    all-reduce — this wrapper is then just identity + API parity
    (`scale_loss`, `no_sync` kept as no-ops because XLA owns scheduling).
    """

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        """Eager multi-process grad allreduce (parity test path)."""
        from .collective import all_reduce_arrays
        params = [p for p in self._layers.parameters() if p.grad is not None]
        if not params or env.get_world_size() <= 1:
            return
        arrays = [p.grad._data for p in params]
        reduced = all_reduce_arrays(arrays)
        n = env.get_world_size()
        for p, arr in zip(params, reduced):
            p.grad._data = arr / n

    def no_sync(self):
        import contextlib
        return contextlib.nullcontext()

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)
