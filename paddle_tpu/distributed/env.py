"""Distributed environment state.

Replaces the reference's env-variable protocol
(PADDLE_TRAINER_ID/PADDLE_TRAINER_ENDPOINTS, reference:
fleet/launch_utils.py) + NCCL comm registry (platform/collective_helper.h:68)
with a process-global registry of the active `jax.sharding.Mesh`, the rank
(process index) and named-axis groups.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

import jax

# jax >= 0.5 promotes shard_map to jax.shard_map (kwargs: check_vma,
# axis_names); older builds keep it in jax.experimental with the check_rep/
# auto spelling. One resolved, kwarg-adapting symbol for every distributed
# module so call sites can use the modern surface unconditionally.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None,
                  axis_names=None, **kw):
        if check_vma is not None and "check_rep" not in kw:
            kw["check_rep"] = check_vma
        if axis_names is not None:
            # modern axis_names lists the MANUAL axes; legacy `auto` lists
            # the complement
            kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
        return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kw)

_state = threading.local()
_global = {
    "mesh": None,           # active jax.sharding.Mesh
    "initialized": False,
    "data_axis": None,      # axis name used for data parallel inside shard_map
}


def get_rank() -> int:
    if _global["initialized"]:
        return jax.process_index()
    return int(os.environ.get("PADDLE_TRAINER_ID", 0))


def get_world_size() -> int:
    if _global["initialized"]:
        return jax.process_count()
    return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))


def set_mesh(mesh):
    _global["mesh"] = mesh


def get_mesh():
    return _global["mesh"]


def mark_initialized():
    _global["initialized"] = True


def is_initialized() -> bool:
    return _global["initialized"]


def reset():
    """Clear process-global distributed state (tests / re-init)."""
    _global["mesh"] = None
    _global["initialized"] = False
    _global["data_axis"] = None


def set_data_axis(name: Optional[str]):
    """Set while tracing inside shard_map so SyncBatchNorm etc. can pmean."""
    _global["data_axis"] = name


def current_data_axis() -> Optional[str]:
    return _global["data_axis"]


# ---------------------------------------------------------------------------
# Bound-axis tracking: collectives consult this to decide traced vs eager.
# The analogue of the reference's "which ring am I on" (ring_id attr on
# c_* ops) — here, which mesh axes the enclosing shard_map bound.
# ---------------------------------------------------------------------------

import contextlib


def _axis_stack():
    if not hasattr(_state, "axes"):
        _state.axes = []
    return _state.axes


@contextlib.contextmanager
def axes_bound(*names: str):
    """Mark mesh axes as bound for the dynamic extent (used by shard_ctx)."""
    stack = _axis_stack()
    stack.extend(names)
    try:
        yield
    finally:
        del stack[len(stack) - len(names):]


def bound_axes():
    return tuple(_axis_stack())
