"""paddle_tpu — a TPU-native deep-learning framework.

A ground-up JAX/XLA/Pallas re-design with the capability surface of the
reference framework (PaddlePaddle ~v2.1, see SURVEY.md): eager-first tensors
with tape autograd, a jit-traced "static" mode, a broad nn/optimizer/data
stack, AMP, checkpointing, and mesh-based distributed training (dp/tp/pp/
sharding/sp) over XLA collectives.

Public namespace mirrors `paddle.*`.
"""

__version__ = "0.3.0"

import jax as _jax

# Production RNG policy: rbg keys — dropout mask generation is ~10x cheaper
# than threefry on TPU and the reference makes no counter-stream promises.
# Respect an explicit user/env override.
import os as _os
if "JAX_DEFAULT_PRNG_IMPL" not in _os.environ:
    try:
        _jax.config.update("jax_default_prng_impl", "rbg")
    except Exception:
        pass

from .core import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    NPUPlace,
    Parameter,
    Place,
    TPUPlace,
    Tensor,
    device_count,
    enable_grad,
    get_device,
    get_flags,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    is_grad_enabled,
    no_grad,
    seed,
    set_device,
    set_flags,
    set_grad_enabled,
)
from .core.dtype import (  # noqa: F401
    bfloat16,
    bool_,
    bool_ as bool,  # noqa: A004  (paddle.bool, reference dtype export)
    complex64,
    complex128,
    float16,
    float32,
    float64,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
import numpy as _np
# paddle.dtype: the dtype TYPE (reference exports the VarType class; jax
# dtypes are numpy dtypes here)
dtype = _np.dtype
from .core.random import get_rng_state, set_rng_state  # noqa: F401
# CUDA-named RNG state shims map to the device-generic generator state
from .core.random import get_rng_state as get_cuda_rng_state  # noqa: F401
from .core.random import set_rng_state as set_cuda_rng_state  # noqa: F401

from .tensor import *  # noqa: F401,F403
from . import tensor  # noqa: F401

from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import metric  # noqa: F401
from . import autograd  # noqa: F401
from . import jit  # noqa: F401
from . import static  # noqa: F401
from . import vision  # noqa: F401
from . import distributed  # noqa: F401
from . import framework  # noqa: F401
from . import profiler as _profiler_mod  # noqa: F401
from . import incubate  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import text  # noqa: F401
from . import linalg  # noqa: F401
from . import distribution  # noqa: F401
from . import regularizer  # noqa: F401
from . import hub  # noqa: F401
from . import utils  # noqa: F401
from . import monitor  # noqa: F401
from . import onnx  # noqa: F401
from . import inference  # noqa: F401
from . import slim  # noqa: F401
from . import device  # noqa: F401
from . import reader  # noqa: F401
from . import cost_model  # noqa: F401
from . import sysconfig  # noqa: F401
from . import compat  # noqa: F401
from . import callbacks  # noqa: F401
from . import version  # noqa: F401

from .framework.io import load, save  # noqa: F401
from .hapi.model import Model  # noqa: F401
from .nn.initializer import ParamAttr  # noqa: F401
from .hapi import summary  # noqa: F401
from .nn.layer import Layer  # noqa: F401
from .autograd.functional import grad  # noqa: F401
from .tensor.einsum import einsum  # noqa: F401
from .distributed.parallel import DataParallel  # noqa: F401

# `paddle.nn.functional` style import convenience
from .nn import functional as _F  # noqa: F401

# Persistent XLA compilation cache (FLAGS_compilation_cache, on by
# default): warm starts skip the 20-40s first compile. User scripts get
# this without opting in; FLAGS_compilation_cache=0 disables.
from .core.flags import apply_compilation_cache as _apply_cc  # noqa: E402
_apply_cc()


def disable_static(place=None):
    """Return to eager (dygraph) mode — the framework default."""
    from .static import _static_mode
    _static_mode[0] = False
    return None


def enable_static():
    from .static import _enable_static_mode
    _enable_static_mode()


def in_dynamic_mode() -> bool:
    from .static import _in_static_mode
    return not _in_static_mode()
