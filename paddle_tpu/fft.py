"""Discrete Fourier transforms.

reference parity: python/paddle/fft.py (fft/ifft/rfft/irfft/hfft/ihfft +
2d/nd variants, fftfreq/rfftfreq, fftshift/ifftshift; norm in
{"backward", "ortho", "forward"}).

TPU-native: thin tape-aware wrappers over jnp.fft — XLA lowers FFTs to the
backend's native FFT ops, so there is nothing to hand-schedule. The `apply`
wrapper keeps eager autograd working (jax.vjp of the fft primitives).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax.numpy as jnp

from .core.tensor import Tensor, apply

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = ("backward", "ortho", "forward")


def _check_norm(norm):
    if norm not in _NORMS:
        raise ValueError(
            f"Unexpected norm: {norm!r}. Norm should be one of {_NORMS}")
    return norm


def _wrap(fn, x, name, **kw):
    x = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    return apply(lambda a: fn(a, **kw), x, name=name)


# -- 1d ---------------------------------------------------------------------

def fft(x, n=None, axis=-1, norm="backward", name=None):
    return _wrap(jnp.fft.fft, x, "fft", n=n, axis=axis,
                 norm=_check_norm(norm))


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    return _wrap(jnp.fft.ifft, x, "ifft", n=n, axis=axis,
                 norm=_check_norm(norm))


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    return _wrap(jnp.fft.rfft, x, "rfft", n=n, axis=axis,
                 norm=_check_norm(norm))


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    return _wrap(jnp.fft.irfft, x, "irfft", n=n, axis=axis,
                 norm=_check_norm(norm))


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    return _wrap(jnp.fft.hfft, x, "hfft", n=n, axis=axis,
                 norm=_check_norm(norm))


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    return _wrap(jnp.fft.ihfft, x, "ihfft", n=n, axis=axis,
                 norm=_check_norm(norm))


# -- 2d ---------------------------------------------------------------------

def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _wrap(jnp.fft.fft2, x, "fft2", s=s, axes=axes,
                 norm=_check_norm(norm))


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _wrap(jnp.fft.ifft2, x, "ifft2", s=s, axes=axes,
                 norm=_check_norm(norm))


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _wrap(jnp.fft.rfft2, x, "rfft2", s=s, axes=axes,
                 norm=_check_norm(norm))


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _wrap(jnp.fft.irfft2, x, "irfft2", s=s, axes=axes,
                 norm=_check_norm(norm))


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return hfftn(x, s=s, axes=axes, norm=norm, name=name)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s=s, axes=axes, norm=norm, name=name)


# -- nd ---------------------------------------------------------------------

def fftn(x, s=None, axes=None, norm="backward", name=None):
    return _wrap(jnp.fft.fftn, x, "fftn", s=s, axes=axes,
                 norm=_check_norm(norm))


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return _wrap(jnp.fft.ifftn, x, "ifftn", s=s, axes=axes,
                 norm=_check_norm(norm))


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    return _wrap(jnp.fft.rfftn, x, "rfftn", s=s, axes=axes,
                 norm=_check_norm(norm))


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    return _wrap(jnp.fft.irfftn, x, "irfftn", s=s, axes=axes,
                 norm=_check_norm(norm))


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """Hermitian-input nd FFT: forward FFT over the leading axes, then a
    Hermitian (real-output) transform on the last axis — the inverse of
    ihfftn (reference: fft.py:729)."""
    def impl(a):
        ax = axes if axes is not None else tuple(range(a.ndim))
        lead_s = None if s is None else s[:-1]
        inner = jnp.fft.fftn(a, s=lead_s, axes=ax[:-1], norm=norm)
        n = None if s is None else s[-1]
        return jnp.fft.hfft(inner, n=n, axis=ax[-1], norm=norm)
    _check_norm(norm)
    return _wrap(impl, x, "hfftn")


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    """Inverse of hfftn: ihfft (real input) on the LAST axis first, then
    inverse FFT over the leading axes (reference: fft.py:781)."""
    def impl(a):
        ax = axes if axes is not None else tuple(range(a.ndim))
        n = None if s is None else s[-1]
        inner = jnp.fft.ihfft(a, n=n, axis=ax[-1], norm=norm)
        lead_s = None if s is None else s[:-1]
        return jnp.fft.ifftn(inner, s=lead_s, axes=ax[:-1], norm=norm)
    _check_norm(norm)
    return _wrap(impl, x, "ihfftn")


# -- helpers ----------------------------------------------------------------

def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(int(n), d=float(d)).astype(
        dtype or jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(int(n), d=float(d)).astype(
        dtype or jnp.float32))


def fftshift(x, axes=None, name=None):
    return _wrap(jnp.fft.fftshift, x, "fftshift", axes=axes)


def ifftshift(x, axes=None, name=None):
    return _wrap(jnp.fft.ifftshift, x, "ifftshift", axes=axes)
