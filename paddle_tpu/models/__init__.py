"""Flagship model families (language models built on the parallel layers).

reference parity: the reference ships vision models in-tree
(python/paddle/vision/models) and its GPT/BERT/ERNIE families through the
fleet meta_parallel layers (fleet/meta_parallel/parallel_layers/mp_layers.py);
here the language models live in-tree as the flagship demonstration of the
TP/DP/SP sharding stack.
"""

from .gpt import (GPTConfig, GPTModel, GPTForPretraining,
                  GPTForPretrainingPipe, GPTPretrainingCriterion, gpt_tiny,
                  gpt2_small, gpt2_medium)
from .bert import (BertConfig, BertModel, BertForMaskedLM, bert_tiny,
                   bert_base)
from .ernie import (ErnieConfig, ErnieModel, ErnieForPretraining,
                    ernie_tiny, ernie_base, ernie_3_1p5b)
from .dlrm import DLRM, DLRMConfig, TableEmbedding, dlrm_tiny

__all__ = [
    "DLRM", "DLRMConfig", "TableEmbedding", "dlrm_tiny",
    "GPTConfig", "GPTModel", "GPTForPretraining", "GPTForPretrainingPipe",
    "GPTPretrainingCriterion",
    "gpt_tiny", "gpt2_small", "gpt2_medium",
    "BertConfig", "BertModel", "BertForMaskedLM", "bert_tiny", "bert_base",
    "ErnieConfig", "ErnieModel", "ErnieForPretraining", "ernie_tiny",
    "ernie_base", "ernie_3_1p5b",
]
