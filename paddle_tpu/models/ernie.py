"""ERNIE: enhanced-representation encoder + pretraining heads
(BASELINE.md config 5: ERNIE-3.0 1.5B hybrid-parallel pretraining).

reference parity: the reference repo carries ERNIE as a model-zoo family
(README model lineup; the in-tree building blocks are the same
TransformerEncoder + fused attention as BERT). Architecturally ERNIE-style
pretraining = BERT encoder + task-type embeddings + MLM with
knowledge-span masking + sentence-order prediction (SOP) head.

TPU-native: built on nn.TransformerEncoder (flash-attention dispatch
inside), task embeddings folded into the input sum, and hybrid-parallel
ready — `apply_hybrid_specs` stamps TP PartitionSpecs by name, ZeRO via
TrainStep(zero_axis=...), so the 1.5B config shards over a dp x mp mesh
without model rewrites.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.flags import matmul_precision
from ..core.tensor import Tensor, apply
from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.layer import Layer
from ..nn.layers.common import Dropout, Embedding, Linear
from ..nn.layers.norm import LayerNorm
from ..nn.layers.transformer import (TransformerEncoder,
                                     TransformerEncoderLayer)

__all__ = ["ErnieConfig", "ErnieModel", "ErnieForPretraining",
           "ernie_tiny", "ernie_base", "ernie_3_1p5b"]


@dataclass
class ErnieConfig:
    vocab_size: int = 18000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 513
    type_vocab_size: int = 2
    task_type_vocab_size: int = 3
    use_task_id: bool = True
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    #: run the encoder stack as one jax.lax.scan over layer-stacked params
    #: (nn.scan; O(1) trace/compile in num_layers, state_dict unchanged)
    scan_layers: bool = True
    use_recompute: bool = False
    #: selective-remat policy name (fleet.utils.recompute.
    #: resolve_checkpoint_policy); None = full remat
    recompute_policy: Optional[str] = None


class ErnieEmbeddings(Layer):
    """word + position + token-type (+ task-type) embeddings."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        init = Normal(0.0, cfg.initializer_range)
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.word_embeddings.weight._data = init(
            (cfg.vocab_size, cfg.hidden_size), "float32")
        self.position_embeddings = Embedding(cfg.max_position_embeddings,
                                             cfg.hidden_size)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size,
                                               cfg.hidden_size)
        if cfg.use_task_id:
            self.task_type_embeddings = Embedding(cfg.task_type_vocab_size,
                                                  cfg.hidden_size)
        self.layer_norm = LayerNorm(cfg.hidden_size)
        self.dropout = Dropout(cfg.hidden_dropout_prob)
        self.cfg = cfg

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                task_type_ids=None):
        S = input_ids.shape[1]
        if position_ids is None:
            from ..tensor.creation import arange
            position_ids = arange(0, S, dtype="int32")
        x = self.word_embeddings(input_ids) + \
            self.position_embeddings(position_ids)
        if token_type_ids is not None:
            x = x + self.token_type_embeddings(token_type_ids)
        if self.cfg.use_task_id and task_type_ids is not None:
            x = x + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.layer_norm(x))


class ErnieModel(Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = ErnieEmbeddings(cfg)
        enc_layer = TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout_prob, activation="gelu",
            attn_dropout=cfg.attention_dropout_prob, act_dropout=0.0,
            normalize_before=False)
        self.encoder = TransformerEncoder(enc_layer, cfg.num_layers)
        self.encoder.enable_scan = cfg.scan_layers
        self.encoder.use_recompute = cfg.use_recompute
        self.encoder.recompute_policy = cfg.recompute_policy
        self.pooler = Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                position_ids=None, task_type_ids=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids,
                            task_type_ids)
        if attention_mask is not None and attention_mask.ndim == 2:
            def to_additive(m):
                return ((1.0 - m.astype(jnp.float32))
                        * -1e30)[:, None, None, :]
            attention_mask = apply(to_additive, attention_mask,
                                   name="ernie_attn_mask")
        seq = self.encoder(x, attention_mask)
        pooled = F.tanh(self.pooler(seq[:, 0]))
        return seq, pooled


class ErnieForPretraining(Layer):
    """MLM head (tied decoder) + sentence-order prediction head."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.ernie = ErnieModel(cfg)
        self.transform = Linear(cfg.hidden_size, cfg.hidden_size)
        self.transform_norm = LayerNorm(cfg.hidden_size)
        self.decoder_bias = self.create_parameter((cfg.vocab_size,),
                                                  is_bias=True)
        self.sop_head = Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_positions=None, task_type_ids=None):
        seq, pooled = self.ernie(input_ids, token_type_ids, attention_mask,
                                 task_type_ids=task_type_ids)
        h = self.transform_norm(F.gelu(self.transform(seq),
                                       approximate=True))
        w = self.ernie.embeddings.word_embeddings.weight
        prec = matmul_precision()

        def head(hh, ww, bb, *mp):
            if mp:
                idx = mp[0].astype(jnp.int32)
                hh = jnp.take_along_axis(hh, idx[..., None], axis=1)
            return jnp.einsum("bme,ve->bmv", hh, ww, precision=prec) + bb

        args = [h, w, self.decoder_bias] + (
            [masked_positions] if masked_positions is not None else [])
        mlm_scores = apply(head, *args, name="ernie_mlm_head")
        sop_scores = self.sop_head(pooled)
        return mlm_scores, sop_scores

    def loss(self, mlm_scores, sop_scores, masked_lm_labels, sop_labels,
             masked_lm_weights=None):
        from ..nn import chunked_ce as _cce
        chunked = _cce.enabled_for(mlm_scores.shape[-1])

        def mlm_ce(lg, lab, *ww):
            # streamed-vocab CE above the threshold (nn/chunked_ce.py),
            # dense logsumexp below — one shared epilogue with BERT
            return _cce.masked_lm_loss(lg, lab, *ww, chunked=chunked)

        args = [mlm_scores, masked_lm_labels] + (
            [masked_lm_weights] if masked_lm_weights is not None else [])
        mlm_loss = apply(mlm_ce, *args, name="ernie_mlm_loss")
        sop_loss = F.cross_entropy(sop_scores, sop_labels)
        return mlm_loss + sop_loss


def ernie_tiny(**kw) -> ErnieConfig:
    d = dict(vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
             intermediate_size=128, max_position_embeddings=128,
             hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    d.update(kw)
    return ErnieConfig(**d)


def ernie_base(**kw) -> ErnieConfig:
    return ErnieConfig(**kw)


def ernie_3_1p5b(**kw) -> ErnieConfig:
    """ERNIE-3.0 1.5B-class config (BASELINE config 5)."""
    d = dict(vocab_size=40000, hidden_size=2048, num_layers=24,
             num_heads=16, intermediate_size=8192,
             max_position_embeddings=2048)
    d.update(kw)
    return ErnieConfig(**d)
