"""DLRM: the deep-learning recommendation model (Naumov et al., 2019).

reference parity: the reference serves this workload shape through its
fleet PS mode (CTR models over DownpourSparseTable embeddings); here
the model is in-tree as the flagship consumer of the
``paddle_tpu.recsys`` giant-embedding subsystem (docs/RECSYS.md):

- dense features → bottom MLP → one ``embedding_dim`` vector;
- each sparse slot's id → an embedding TABLE lookup — the tables are
  SparseTable-protocol objects (host :class:`SparseTable`,
  :class:`SSDSparseTable`, :class:`~paddle_tpu.recsys.
  TieredEmbeddingTable`, :class:`~paddle_tpu.recsys.
  ShardedEmbeddingTable`), NOT dense Parameters: dense optimizers skip
  them, gradients stream into the tables through the backward tape
  (the PS push path), exactly like ``DistributedEmbedding``;
- pairwise-dot feature interaction over the stacked vectors (upper
  triangle), concatenated with the bottom output;
- top MLP → one click logit.

The embedding phase is timed per forward (``last_timings``) so the
serving engine can attribute lookup latency separately from MLP
compute.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple, Union

import jax
import numpy as np

from ..core.tensor import (Tensor, TapeNode, _wrap_outputs,
                           is_grad_enabled)
from ..nn.layer import Layer, LayerList, Sequential
from .. import nn
from ..nn import functional as F

__all__ = ["DLRMConfig", "TableEmbedding", "DLRM", "dlrm_tiny"]


class TableEmbedding(Layer):
    """Embedding over a SparseTable-protocol table with a device-array
    fast path: forward uses ``table.lookup`` (jnp rows, no host round
    trip for HBM-resident tables) when present, else ``table.pull``;
    backward pushes the row gradients into the table (the PS push).
    Eager-only, like ``DistributedEmbedding`` — the table lives outside
    the compiled program."""

    def __init__(self, table):
        super().__init__()
        self.table = table
        self.embedding_dim = int(table.dim)

    def forward(self, ids) -> Tensor:
        from ..core.tensor import _is_tracer
        raw = ids._data if isinstance(ids, Tensor) else ids
        if _is_tracer(raw):
            raise RuntimeError(
                "TableEmbedding pulls from a PS table and is eager-only; "
                "keep it outside jit/TrainStep (feed its output as a "
                "batch input)")
        ids_np = np.asarray(raw)
        lookup = getattr(self.table, "lookup", None)
        if lookup is not None:
            rows = lookup(ids_np.reshape(-1))
        else:
            import jax.numpy as jnp
            rows = jnp.asarray(self.table.pull(ids_np.reshape(-1)))
        out = rows.reshape(ids_np.shape + (self.embedding_dim,))
        node = None
        if is_grad_enabled():
            push = self.table.push

            def vjp_fn(g, ids_np=ids_np):
                push(ids_np.reshape(-1), np.asarray(g))
                return ()

            node = TapeNode(vjp_fn, [],
                            [jax.ShapeDtypeStruct(out.shape, out.dtype)],
                            name="recsys_embedding")
        return _wrap_outputs(out, node=node)


@dataclass
class DLRMConfig:
    num_dense: int = 4
    num_sparse: int = 8
    #: one vocab for every slot, or a per-slot list
    vocab_sizes: Union[int, Sequence[int]] = 10_000
    embedding_dim: int = 16
    bottom_mlp: Tuple[int, ...] = (32,)
    top_mlp: Tuple[int, ...] = (32,)

    def vocab_list(self) -> List[int]:
        v = self.vocab_sizes
        if isinstance(v, (int, np.integer)):
            return [int(v)] * self.num_sparse
        if len(v) != self.num_sparse:
            raise ValueError("vocab_sizes must match num_sparse")
        return [int(x) for x in v]


def _mlp(sizes: Sequence[int], final_act: bool) -> Sequential:
    layers: list = []
    for i in range(len(sizes) - 1):
        layers.append(nn.Linear(sizes[i], sizes[i + 1]))
        if final_act or i < len(sizes) - 2:
            layers.append(nn.ReLU())
    return Sequential(*layers)


class DLRM(Layer):
    """``forward(dense [B, num_dense], ids [B, num_sparse]) -> logits
    [B]``. ``tables`` injects the embedding stores (one per sparse
    slot, or one shared); default = per-slot host ``SparseTable``."""

    def __init__(self, config: DLRMConfig, tables: Optional[list] = None,
                 table_optimizer: str = "adagrad", table_lr: float = 0.05,
                 seed: int = 0):
        super().__init__()
        self.cfg = config
        vocabs = config.vocab_list()
        D = config.embedding_dim
        if tables is None:
            from ..distributed.ps import SparseTable
            tables = [SparseTable(v, D, optimizer=table_optimizer,
                                  lr=table_lr, seed=seed + f)
                      for f, v in enumerate(vocabs)]
        elif len(tables) == 1 and config.num_sparse > 1:
            tables = list(tables) * config.num_sparse   # one shared table
        if len(tables) != config.num_sparse:
            raise ValueError(
                f"need {config.num_sparse} tables (or 1 shared), got "
                f"{len(tables)}")
        for t in tables:
            if int(t.dim) != D:
                raise ValueError("every table's dim must equal "
                                 f"embedding_dim={D}")
        self.embeddings = LayerList([TableEmbedding(t) for t in tables])
        self.bottom = _mlp((config.num_dense,) + tuple(config.bottom_mlp)
                           + (D,), final_act=True)
        F_feat = config.num_sparse + 1
        self._triu = np.triu_indices(F_feat, k=1)
        n_pairs = len(self._triu[0])
        self.top = _mlp((D + n_pairs,) + tuple(config.top_mlp) + (1,),
                        final_act=False)
        #: wall-clock split of the last eager forward — the serving
        #: engine's lookup-vs-rank latency attribution
        self.last_timings = {"lookup_s": 0.0, "mlp_s": 0.0}

    @property
    def tables(self) -> list:
        return [e.table for e in self.embeddings]

    def forward(self, dense, ids) -> Tensor:
        import paddle_tpu as paddle
        t0 = time.perf_counter()
        ids_np = np.asarray(ids._data if isinstance(ids, Tensor) else ids)
        if ids_np.ndim != 2 or ids_np.shape[1] != self.cfg.num_sparse:
            raise ValueError(
                f"ids must be [B, {self.cfg.num_sparse}], got "
                f"{ids_np.shape}")
        embs = [emb(ids_np[:, f])
                for f, emb in enumerate(self.embeddings)]
        t1 = time.perf_counter()
        x = self.bottom(dense if isinstance(dense, Tensor)
                        else paddle.to_tensor(np.asarray(dense,
                                                         np.float32)))
        z = paddle.stack([x] + embs, axis=1)         # [B, F+1, D]
        inter = paddle.matmul(z, paddle.transpose(z, [0, 2, 1]))
        flat = paddle.reshape(inter, [inter.shape[0], -1])
        F_feat = self.cfg.num_sparse + 1
        pair_idx = self._triu[0] * F_feat + self._triu[1]
        pairs = paddle.index_select(
            flat, paddle.to_tensor(pair_idx.astype(np.int64)), axis=1)
        top_in = paddle.concat([x, pairs], axis=-1)
        logits = paddle.reshape(self.top(top_in), [-1])
        t2 = time.perf_counter()
        self.last_timings = {"lookup_s": t1 - t0, "mlp_s": t2 - t1}
        return logits

    def loss(self, dense, ids, labels) -> Tensor:
        import paddle_tpu as paddle
        logits = self(dense, ids)
        lab = labels if isinstance(labels, Tensor) else paddle.to_tensor(
            np.asarray(labels, np.float32))
        return F.binary_cross_entropy_with_logits(logits, lab)


def dlrm_tiny(**over) -> DLRMConfig:
    """Test-scale config (the gpt_tiny convention)."""
    kw = dict(num_dense=4, num_sparse=4, vocab_sizes=512,
              embedding_dim=8, bottom_mlp=(16,), top_mlp=(16,))
    kw.update(over)
    return DLRMConfig(**kw)
