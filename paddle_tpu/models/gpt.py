"""GPT: decoder-only language model — the flagship of the parallel stack.

reference parity: the reference trains GPT through
fleet/meta_parallel/parallel_layers/mp_layers.py (VocabParallelEmbedding:30,
ColumnParallelLinear:97, RowParallelLinear:170, ParallelCrossEntropy:249)
plus the fused attention kernels (paddle/fluid/operators/fused/
fused_attention_op.cu, fused_feedforward_op.cu), wiring NCCL allreduces by
hand between the sharded matmuls.

TPU-native design (GSPMD, single logical program):
- Every parameter is the FULL logical array annotated with a PartitionSpec
  on the ``mp`` mesh axis (QKV/MLP-in column-sharded, attn-out/MLP-out
  row-sharded, vocab embedding row-sharded). Under jit over a mesh, XLA's
  SPMD partitioner lays the weights out and inserts the same psums the
  reference's c_allreduce_sum ops perform — no hand-written collectives.
- QKV is ONE fused matmul ([E] x [E, 3·H·D]) for MXU utilisation; the
  weight is stored [E, 3, H, D] so the mp sharding rides the head axis and
  the reshape to per-head layout is communication-free.
- Attention routes through ops.attention (Pallas flash kernel when
  eligible, fused XLA softmax otherwise), causal.
- The LM head ties the vocab-parallel embedding weight; logits stay
  vocab-sharded into ParallelCrossEntropy (the c_softmax_with_cross_entropy
  pattern) so the [B, S, V] logits tensor is never materialised replicated.
- ``use_recompute`` wraps each block in jax.checkpoint (reference:
  fleet/utils/recompute.py) to trade FLOPs for HBM.
- ``sequence_parallel`` pins the residual stream's seq axis to the ``sp``
  mesh axis so LayerNorm/dropout activations are sequence-sharded
  (reference: sequence_parallel_utils.py scatter/gather pattern).
"""

from __future__ import annotations

import collections
import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.flags import matmul_precision
from ..core.tensor import apply
from ..distributed import env as dist_env
from ..distributed.fleet.utils.recompute import recompute
from ..distributed.meta_parallel.parallel_layers.mp_layers import (
    VocabParallelEmbedding, ParallelCrossEntropy)
from ..nn import functional as F
from ..nn.initializer import Constant, Normal
from ..nn.layer import Layer, LayerList
from ..nn.layers.common import Dropout, Embedding
from ..nn.layers.norm import LayerNorm
from ..nn.scan import (can_scan_layers, note_scan_fallback, scan_layers,
                       scan_layers_with_cache)

__all__ = ["GPTConfig", "GPTModel", "GPTForPretraining", "GPTForPretrainingPipe",
           "GPTPretrainingCriterion", "GPTMoEDecoderLayer",
           "gpt_tiny", "gpt2_small", "gpt2_medium", "gpt2_large", "gpt2_xl"]

MP = "mp"
SP = "sp"


@dataclass
class GPTConfig:
    vocab_size: int = 50304           # padded to a multiple of 128 for the MXU
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: Optional[int] = None   # default 4*hidden
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    use_recompute: bool = False
    #: selective-remat policy name for use_recompute (see
    #: fleet.utils.recompute.resolve_checkpoint_policy); None = full remat.
    #: 'dots_with_no_batch_dims_saveable' keeps MXU outputs resident and
    #: rematerializes only the elementwise tail — the TPU default trade.
    recompute_policy: Optional[str] = None
    #: run the decoder stack as one jax.lax.scan over layer-stacked params
    #: (nn.scan): O(1) trace+compile in num_layers, per-layer state_dict
    #: names and LayerList API unchanged. Falls back to the Python loop for
    #: KV-cache decoding or heterogeneous stacks.
    scan_layers: bool = True
    sequence_parallel: bool = False
    #: Mixture-of-Experts (ISSUE 10, docs/MOE.md): moe_experts > 0 swaps
    #: the FFN of every ``moe_every``-th decoder layer (layer i is MoE
    #: iff (i+1) % moe_every == 0; moe_every=1 = every layer, the
    #: homogeneous stack that scans as ONE lax.scan) for an
    #: incubate.moe.MoELayer with ``moe_experts`` stacked ExpertFFN
    #: experts (hidden = ffn_size), top-``moe_top_k`` routing at
    #: ``moe_capacity_factor``. The router aux/z losses are weighted by
    #: moe_aux_weight/moe_z_weight into ``GPTModel.moe_loss()``; add it
    #: to the CE in the training loss_fn. Dense layer state_dict names
    #: are unchanged; MoE layers add ``layers.<i>.moe.*`` leaves.
    moe_experts: int = 0
    moe_every: int = 1
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    moe_aux_weight: float = 1e-2
    moe_z_weight: float = 1e-3

    @property
    def ffn_size(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def moe_layer_indices(self):
        """Decoder-layer indices that carry an MoE FFN."""
        if not self.moe_experts:
            return []
        k = max(1, int(self.moe_every))
        return [i for i in range(self.num_layers) if (i + 1) % k == 0]


def _mesh():
    return dist_env.get_mesh()


# shared layout-pin helper; BATCH expands to the composite data axes
# (('dp', 'sharding')) so activation pins agree with TrainStep's data_spec
from ..distributed.spmd import BATCH, constrain as _constrain  # noqa: E402


def _seq_spec(cfg) -> Optional[str]:
    """Mesh axis for the sequence dim of the residual stream (or None)."""
    if not cfg.sequence_parallel:
        return None
    mesh = _mesh()
    if mesh is not None and SP in mesh.axis_names:
        return SP
    return None


class GPTAttention(Layer):
    """Causal self-attention with ONE fused QKV matmul, head-sharded over mp.

    reference: fused_attention_op.cu computes qkv in one gemm then runs the
    fmha kernel; mp_layers.py shards qkv column-wise + out row-wise. Here the
    qkv weight is [E, 3, H, D] with spec P(None, None, 'mp', None): one
    logical gemm, head axis sharded, zero-copy reshape to [B, S, H, D].
    """

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        E, H, D = cfg.hidden_size, cfg.num_heads, cfg.head_dim
        self.cfg = cfg
        self.num_heads, self.head_dim = H, D
        init = Normal(0.0, cfg.initializer_range)
        # scaled init for the residual-out projection (GPT-2 paper)
        out_init = Normal(0.0, cfg.initializer_range / math.sqrt(2 * cfg.num_layers))
        self.qkv_weight = self.create_parameter((E, 3, H, D),
                                                default_initializer=init)
        self.qkv_weight.spec = P(None, None, MP, None)
        self.qkv_bias = self.create_parameter((3, H, D), is_bias=True)
        self.qkv_bias.spec = P(None, MP, None)
        self.out_weight = self.create_parameter((H, D, E),
                                                default_initializer=out_init)
        self.out_weight.spec = P(MP, None, None)
        self.out_bias = self.create_parameter((E,), is_bias=True)
        self.out_bias.spec = P()

    #: fixed-size KV buffers [B, L_max, H, D] for jit-compatible decoding
    #: (reference generation uses growing concat caches; on TPU a static
    #: buffer + dynamic_update_slice keeps every decode step the same
    #: compiled program)
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def forward(self, x, cache=None, pos=None):
        cfg = self.cfg
        prec = matmul_precision()

        def qkv_fn(h, w, b):
            y = jnp.einsum("bse,ethd->bsthd", h, w, precision=prec) + b
            return y

        qkv = apply(qkv_fn, x, self.qkv_weight, self.qkv_bias, name="fused_qkv")
        # the serving layer is only imported once a paged cache actually
        # arrives — training forwards (cache=None) never touch it
        is_paged = False
        if cache is not None and \
                not isinstance(cache, GPTAttention.StaticCache):
            from ..serving.kv_cache import PagedLayerCache
            is_paged = isinstance(cache, PagedLayerCache)
        if is_paged and cache.lora_a is not None:
            # multi-tenant LoRA (serving.lora): per-slot adapter deltas
            # on the fused QKV projection, batched over adapters via
            # bgmv. Absent pools (the default) add nothing to the graph.
            qkv = qkv + self._lora_delta(x, cache)
        qkv = _constrain(qkv, BATCH, None, None, MP, None)
        from ..tensor.manipulation import split as tsplit, squeeze
        q, k, v = (squeeze(t, 2) for t in tsplit(qkv, 3, axis=2))

        if is_paged:
            out, cache = self._paged_attention(x, q, k, v, cache, pos)
        elif isinstance(cache, GPTAttention.StaticCache):
            # write this chunk's K/V into the preallocated buffers at pos
            def upd(buf, new, p):
                return jax.lax.dynamic_update_slice(
                    buf, new.astype(buf.dtype),
                    (0, p.astype(jnp.int32), 0, 0))

            kb = apply(upd, cache.k, k, pos, name="kv_cache_update")
            vb = apply(upd, cache.v, v, pos, name="kv_cache_update")
            cache = GPTAttention.StaticCache(kb, vb)
            S = x.shape[1]
            L = kb.shape[1]

            # row i of the chunk sees cache slots j <= pos + i
            def mk_mask(p):
                rows = p + jnp.arange(S, dtype=jnp.int32)[:, None]
                cols = jnp.arange(L, dtype=jnp.int32)[None, :]
                return jnp.where(cols <= rows, 0.0, -1e30)[None, None]

            mask = apply(mk_mask, pos, name="kv_cache_mask")
            from ..ops.attention import scaled_dot_product_attention
            out = scaled_dot_product_attention(
                q, kb, vb, attn_mask=mask, dropout_p=0.0, is_causal=False,
                training=False)
        else:
            if cache is not None:
                from ..tensor.manipulation import concat
                k = concat([cache[0], k], axis=1)
                v = concat([cache[1], v], axis=1)
                cache = (k, v)

            from ..ops.attention import scaled_dot_product_attention
            out = scaled_dot_product_attention(
                q, k, v, dropout_p=cfg.attention_dropout_prob,
                is_causal=True, training=self.training)   # [B, S, H, D]
        out = _constrain(out, BATCH, None, MP, None)

        def out_fn(o, w, b):
            return jnp.einsum("bshd,hde->bse", o, w, precision=prec) + b

        y = apply(out_fn, out, self.out_weight, self.out_bias, name="attn_out")
        return (y, cache) if cache is not None else y

    def _lora_delta(self, x, cache):
        """Batched-LoRA delta for the fused QKV projection
        (serving.lora, ISSUE 17): each slot's adapter row of the stacked
        ``[A, r, E]`` / ``[A, r, 3*H*D]`` pools is gathered + applied by
        the bgmv kernel (``FLAGS_pallas_bgmv``; off = the bit-compatible
        XLA gather+einsum oracle). Returns ``[B, S, 3, H, D]`` in x's
        dtype — row-0 (zero-adapter) slots contribute exactly 0.0."""
        from ..ops import pallas as pallas_ops
        # dispatch resolved OUTSIDE the traced fn, like paged_decode
        use_kernel = pallas_ops.kernel_enabled("bgmv")
        H, D = self.num_heads, self.head_dim

        def delta_fn(h, a, b, ids):
            if use_kernel:
                from ..ops.pallas.bgmv import bgmv as _bgmv
            else:
                from ..ops.pallas.bgmv import bgmv_xla as _bgmv
            d = _bgmv(h, a, b, ids.astype(jnp.int32))     # [B, S, 3*H*D]
            return d.reshape(d.shape[0], d.shape[1], 3, H, D)

        return apply(delta_fn, x, cache.lora_a, cache.lora_b,
                     cache.lora_ids, name="lora_qkv_delta")

    def _paged_attention(self, x, q, k, v, cache, pos):
        """Block-table K/V path (paddle_tpu.serving, ISSUE 6).

        ``cache``: :class:`~paddle_tpu.serving.kv_cache.PagedLayerCache`
        (``[P, bs, H, D]`` pools + ``[B, MB]`` block table); ``pos``:
        per-slot write positions ``[B]``. The chunk's K/V scatter into
        the pools at logical positions ``pos + 0..S-1`` (a bucketed
        prefill's padded tail routes to the scratch page). Prefill
        (S > 1, fresh slots) attends causally over its own K/V — the
        exact math of the full-context forward; decode (S == 1) gathers
        the slot's pages and masks columns past ``pos``, i.e.
        PagedAttention as one XLA gather + masked SDPA.

        A quantized cache (``cache.k_scale is not None``,
        ``FLAGS_serve_kv_quant=int8``) quantizes at write time and
        dequantizes at every page read — both the Pallas decode kernel
        and the XLA gather fallback — so the two dispatch paths stay
        token-exact against each other.
        """
        from ..serving.kv_cache import (PagedLayerCache, gather_pages,
                                        gather_pages_quant, write_pages,
                                        write_pages_quant)

        quant = cache.k_scale is not None
        if quant:
            def updq(pages, scales, new, table, p):
                return write_pages_quant(pages, scales, new, table, p)

            kp, ksc = apply(updq, cache.k_pages, cache.k_scale, k,
                            cache.block_table, pos,
                            name="paged_kv_write_quant")
            vp, vsc = apply(updq, cache.v_pages, cache.v_scale, v,
                            cache.block_table, pos,
                            name="paged_kv_write_quant")
        else:
            def upd(pages, new, table, p):
                return write_pages(pages, new, table, p)

            kp = apply(upd, cache.k_pages, k, cache.block_table, pos,
                       name="paged_kv_write")
            vp = apply(upd, cache.v_pages, v, cache.block_table, pos,
                       name="paged_kv_write")
            ksc = vsc = None
        from ..serving.kv_cache import ContextPagedLayerCache
        is_ctx = isinstance(cache, ContextPagedLayerCache)
        new_cache = type(cache)(kp, vp, cache.block_table, ksc, vsc,
                                cache.lora_a, cache.lora_b, cache.lora_ids)
        S = x.shape[1]
        if S > 1 and not is_ctx:
            from ..ops.attention import scaled_dot_product_attention
            out = scaled_dot_product_attention(
                q, k, v, dropout_p=0.0, is_causal=True, training=False)
            return out, new_cache
        if S > 1:
            # CONTEXT prefill (ISSUE 15): the chunk starts at pos > 0 —
            # a chunked-prefill continuation, a prefix-cache-hit tail or
            # a speculative verify window — so row i must see every
            # page-resident position <= pos + i, not just its own
            # chunk. Same gather + additive-mask construction as the
            # S == 1 decode fallback, one row of mask per chunk row.
            def _ctx_mask(n_cols, p):
                cols = jnp.arange(n_cols, dtype=jnp.int32)
                rows = (p[:, None].astype(jnp.int32)
                        + jnp.arange(S, dtype=jnp.int32)[None, :])
                return jnp.where(
                    cols[None, None, :] <= rows[:, :, None],
                    0.0, -1e30)[:, None]          # [B, 1, S, MB*bs]

            if quant:
                def attend_ctx_q(q_, kpages, kscales, vpages, vscales,
                                 table, p):
                    from ..ops.attention import sdpa_array
                    gk = gather_pages_quant(kpages, kscales, table)
                    gv = gather_pages_quant(vpages, vscales, table)
                    mask = _ctx_mask(gk.shape[1], p)
                    return sdpa_array(q_, gk, gv, mask=mask,
                                      dropout_p=0.0, is_causal=False)

                out = apply(attend_ctx_q, q, kp, ksc, vp, vsc,
                            cache.block_table, pos,
                            name="paged_context_attention_quant")
                return out, new_cache

            def attend_ctx(q_, kpages, vpages, table, p):
                from ..ops.attention import sdpa_array
                from ..serving.kv_cache import gather_pages as _gp
                gk = _gp(kpages, table)
                gv = _gp(vpages, table)
                mask = _ctx_mask(gk.shape[1], p)
                return sdpa_array(q_, gk, gv, mask=mask, dropout_p=0.0,
                                  is_causal=False)

            out = apply(attend_ctx, q, kp, vp, cache.block_table, pos,
                        name="paged_context_attention")
            return out, new_cache

        # decode kernel dispatch resolved OUTSIDE the traced fn so the
        # path choice is stable for any cached trace (kill switch:
        # FLAGS_pallas_paged_decode -> the gather+SDPA composition)
        from ..ops import pallas as pallas_ops
        use_kernel = pallas_ops.kernel_enabled("paged_decode")

        def _decode_mask(n_cols, p):
            cols = jnp.arange(n_cols, dtype=jnp.int32)
            # additive key mask [B, 1, 1, Lk]: slot b sees written
            # positions 0..p[b] (its current token included)
            return jnp.where(cols[None, :] <= p[:, None].astype(jnp.int32),
                             0.0, -1e30)[:, None, None, :]

        if quant:
            def attend_q(q_, kpages, kscales, vpages, vscales, table, p):
                if use_kernel:
                    from ..ops.pallas.paged_decode import \
                        paged_decode_attention_quant
                    o = paged_decode_attention_quant(
                        q_[:, 0], kpages, kscales, vpages, vscales,
                        table, p.astype(jnp.int32),
                        scale=1.0 / math.sqrt(q_.shape[-1]))
                    return o[:, None]
                from ..ops.attention import sdpa_array
                gk = gather_pages_quant(kpages, kscales, table)
                gv = gather_pages_quant(vpages, vscales, table)
                mask = _decode_mask(gk.shape[1], p)
                return sdpa_array(q_, gk, gv, mask=mask, dropout_p=0.0,
                                  is_causal=False)

            out = apply(attend_q, q, kp, ksc, vp, vsc, cache.block_table,
                        pos, name="paged_attention_quant")
            return out, new_cache

        def attend(q_, kpages, vpages, table, p):
            if use_kernel:
                # pages read in place via the block table: the gathered
                # [B, MB*bs, H, D] context never materializes in HBM
                from ..ops.pallas.paged_decode import paged_decode_attention
                o = paged_decode_attention(
                    q_[:, 0], kpages, vpages, table,
                    p.astype(jnp.int32),
                    scale=1.0 / math.sqrt(q_.shape[-1]))
                return o[:, None]
            from ..ops.attention import sdpa_array
            gk = gather_pages(kpages, table)
            gv = gather_pages(vpages, table)
            mask = _decode_mask(gk.shape[1], p)
            return sdpa_array(q_, gk, gv, mask=mask, dropout_p=0.0,
                              is_causal=False)

        out = apply(attend, q, kp, vp, cache.block_table, pos,
                    name="paged_attention")
        return out, new_cache


class GPTMLP(Layer):
    """FFN: column-sharded in-proj, gelu, row-sharded out-proj.

    reference: fused_feedforward_op.cu; mp_layers.py Column+RowParallelLinear
    pair. Full logical weights, specs on the ffn axis; XLA inserts the psum
    after the second matmul."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        E, FF = cfg.hidden_size, cfg.ffn_size
        init = Normal(0.0, cfg.initializer_range)
        out_init = Normal(0.0, cfg.initializer_range / math.sqrt(2 * cfg.num_layers))
        self.w_in = self.create_parameter((E, FF), default_initializer=init)
        self.w_in.spec = P(None, MP)
        self.b_in = self.create_parameter((FF,), is_bias=True)
        self.b_in.spec = P(MP)
        self.w_out = self.create_parameter((FF, E), default_initializer=out_init)
        self.w_out.spec = P(MP, None)
        self.b_out = self.create_parameter((E,), is_bias=True)
        self.b_out.spec = P()

    def forward(self, x):
        h = F.linear(x, self.w_in, self.b_in)
        h = _constrain(h, BATCH, None, MP)
        h = F.gelu(h, approximate=True)
        y = F.linear(h, self.w_out, None)
        y = _constrain(y, BATCH, None, None)
        return y + self.b_out


class GPTDecoderLayer(Layer):
    """Pre-LN block: x + attn(ln1(x)); x + mlp(ln2(x))."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.ln1 = LayerNorm(cfg.hidden_size)
        self.attn = GPTAttention(cfg)
        self.ln2 = LayerNorm(cfg.hidden_size)
        self._build_ffn(cfg)
        self.dropout1 = Dropout(cfg.hidden_dropout_prob)
        self.dropout2 = Dropout(cfg.hidden_dropout_prob)

    def _build_ffn(self, cfg: GPTConfig):
        self.mlp = GPTMLP(cfg)

    def _ffn(self, h):
        """The block's feed-forward half (GPTMoEDecoderLayer swaps in
        the expert mixture)."""
        return self.mlp(h)

    def forward(self, x, cache=None, pos=None):
        sp = _seq_spec(self.cfg)
        if cache is None:
            a = self.attn(self.ln1(x))
        else:
            a, cache = self.attn(self.ln1(x), cache, pos=pos)
        x = x + self.dropout1(a)
        if sp:
            x = _constrain(x, BATCH, sp, None)
        x = x + self.dropout2(self._ffn(self.ln2(x)))
        if sp:
            x = _constrain(x, BATCH, sp, None)
        return x if cache is None else (x, cache)


class GPTMoEDecoderLayer(GPTDecoderLayer):
    """Pre-LN block whose FFN is a mixture of experts (incubate.moe).

    Forward contract: without a cache it returns ``(x, moe_vec)`` where
    ``moe_vec`` is the layer's [aux, z, drop, entropy, balance,
    load_0..E-1] f32 vector — GPTModel collects these (as scan side
    outputs for homogeneous stacks) into ``moe_loss()`` and the router
    telemetry; with a cache it returns ``(x, cache)`` exactly like the
    dense layer, so every decode path is unchanged."""

    def _build_ffn(self, cfg: GPTConfig):
        from ..incubate.moe import MoELayer
        self.moe = MoELayer(
            cfg.hidden_size, num_experts=cfg.moe_experts,
            d_hidden=cfg.ffn_size, top_k=cfg.moe_top_k,
            capacity_factor=cfg.moe_capacity_factor)

    def _ffn(self, h):
        return self.moe(h)

    def forward(self, x, cache=None, pos=None):
        out = super().forward(x, cache, pos=pos)
        if cache is not None:
            return out                    # (x, cache) — decode unchanged
        return out, self.moe.moe_vec


def _paged_body(cls, template, x, cache_slices, extras, scan_in):
    """Shared core of the paged scan bodies: rebuild one layer's cache
    view from the scanned slices and run the block.

    ``cache_slices`` is ``(k, v)`` or — quantized cache
    (``FLAGS_serve_kv_quant``) — ``(k, v, k_scale, v_scale)``;
    ``extras`` is ``(block_table, pos)`` plus, when the LoRA ``scan_in``
    pools ride along, the broadcast ``lora_ids``. Layout changes key
    distinct traces via the scan token's ``(n_cache, n_scan_in,
    len(extra))`` components."""
    if len(cache_slices) == 4:
        k_pages, v_pages, ksc, vsc = cache_slices
    else:
        (k_pages, v_pages), ksc, vsc = cache_slices, None, None
    block_table, pos = extras[0], extras[1]
    la = lb = ids = None
    if scan_in:
        la, lb = scan_in
        ids = extras[2]
    x, c = template(x, cls(k_pages, v_pages, block_table, ksc, vsc,
                           la, lb, ids), pos=pos)
    if ksc is not None:
        return x, (c.k_pages, c.v_pages, c.k_scale, c.v_scale)
    return x, (c.k_pages, c.v_pages)


def _paged_scan_body(template, x, cache_slices, extras, scan_in=()):
    """scan_layers_with_cache adapter for GPT blocks: one layer's page
    pools in, the block's updated pools out (module-level so its identity
    is stable in the eager jit-cache token)."""
    from ..serving.kv_cache import PagedLayerCache
    return _paged_body(PagedLayerCache, template, x, cache_slices,
                       extras, scan_in)


def _paged_scan_body_ctx(template, x, cache_slices, extras, scan_in=()):
    """Context-prefill twin of :func:`_paged_scan_body` (ISSUE 15): the
    layer cache is the :class:`ContextPagedLayerCache` marker, so S>1
    chunks attend over prior pages. A distinct module-level function —
    its identity keys the scan cache token, so the two attention paths
    can never share a trace."""
    from ..serving.kv_cache import ContextPagedLayerCache
    return _paged_body(ContextPagedLayerCache, template, x, cache_slices,
                       extras, scan_in)


class GPTModel(Layer):
    """Embeddings + N decoder blocks + final LN. Returns hidden states."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.word_embeddings = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size)
        # re-init with the model's initializer_range
        self.word_embeddings.weight._data = Normal(0.0, cfg.initializer_range)(
            (cfg.vocab_size, cfg.hidden_size), "float32")
        self.position_embeddings = Embedding(
            cfg.max_position_embeddings, cfg.hidden_size)
        self.position_embeddings.weight._data = Normal(
            0.0, cfg.initializer_range)(
            (cfg.max_position_embeddings, cfg.hidden_size), "float32")
        self.embedding_dropout = Dropout(cfg.hidden_dropout_prob)
        moe_idx = set(cfg.moe_layer_indices())
        if cfg.moe_experts and not moe_idx:
            raise ValueError(
                f"moe_experts={cfg.moe_experts} but moe_every="
                f"{cfg.moe_every} places no MoE layer in a "
                f"{cfg.num_layers}-layer stack (layer i is MoE iff "
                "(i+1) % moe_every == 0)")
        self.layers = LayerList([
            GPTMoEDecoderLayer(cfg) if i in moe_idx else
            GPTDecoderLayer(cfg) for i in range(cfg.num_layers)])
        for i in sorted(moe_idx):
            self.layers[i].moe._label = f"layer{i}"
        self.final_norm = LayerNorm(cfg.hidden_size)

    def forward(self, input_ids, position_ids=None, caches=None,
                cache_pos=None):
        paged = False
        if caches is not None:
            # deferred so training runs never import the serving layer
            from ..serving.kv_cache import PagedCacheView
            paged = isinstance(caches, PagedCacheView)
        B, S = input_ids.shape
        if position_ids is None:
            from ..tensor.creation import arange
            if paged:
                # per-slot positions: slot b's chunk occupies
                # cache_pos[b] .. cache_pos[b]+S-1
                def pos_ids(p):
                    return (p[:, None].astype(jnp.int32)
                            + jnp.arange(S, dtype=jnp.int32)[None, :])

                position_ids = apply(pos_ids, cache_pos,
                                     name="paged_position_ids")
            elif cache_pos is not None:
                position_ids = cache_pos + arange(0, S, dtype="int32")
            else:
                start = 0 if caches is None else caches[0][0].shape[1]
                position_ids = arange(start, start + S, dtype="int32")
        x = self.word_embeddings(input_ids) + \
            self.position_embeddings(position_ids)
        x = self.embedding_dropout(x)
        sp = _seq_spec(self.cfg)
        if sp:
            x = _constrain(x, BATCH, sp, None)

        if paged:
            return self._forward_paged(x, caches, cache_pos)
        if caches is not None and cache_pos is None and \
                isinstance(caches[0], GPTAttention.StaticCache):
            raise ValueError(
                "StaticCache decoding needs cache_pos (the write offset "
                "into the fixed-size KV buffers); models/generation.py "
                "threads it automatically")
        new_caches = [] if caches is not None else None
        if caches is None:
            self.__dict__["_moe_vecs"] = None
        moe_stack = bool(self.cfg.moe_experts) and caches is None
        if caches is None and self.cfg.scan_layers \
                and can_scan_layers(self.layers):
            # one lax.scan over the layer-stacked params: the block body
            # traces/compiles once regardless of depth; selective remat
            # composes inside the scanned body. A homogeneous MoE stack
            # (moe_every=1) threads its per-layer router vectors out of
            # the scan as side outputs (nn.scan num_aux).
            all_moe = isinstance(self.layers[0], GPTMoEDecoderLayer)
            if all_moe:
                from ..core.flags import get_flag as _gf
                x, vecs = scan_layers(
                    self.layers, x,
                    use_recompute=self.cfg.use_recompute and self.training,
                    policy=self.cfg.recompute_policy, num_aux=1,
                    token_extra=(str(_gf("moe_dispatch")),
                                 bool(_gf("moe_expert_parallel")),
                                 int(_gf("moe_a2a_chunks"))),
                    name="gpt_moe_scan_layers")
                self.__dict__["_moe_vecs"] = vecs          # [L, 5+E]
            else:
                x = scan_layers(
                    self.layers, x,
                    use_recompute=self.cfg.use_recompute and self.training,
                    policy=self.cfg.recompute_policy,
                    name="gpt_scan_layers")
        else:
            if caches is not None and self.cfg.scan_layers \
                    and can_scan_layers(self.layers):
                # legacy per-layer StaticCache/tuple decode cannot ride
                # the scan (per-layer python cache objects); the paged
                # layout (paddle_tpu.serving) can — make the silent
                # degradation loud (ISSUE 6 satellite)
                note_scan_fallback("legacy_static_cache", "gpt")
            vecs = []
            for i, blk in enumerate(self.layers):
                is_moe = isinstance(blk, GPTMoEDecoderLayer)
                if caches is not None:
                    x, c = blk(x, caches[i], pos=cache_pos)
                    new_caches.append(c)
                    continue
                if self.cfg.use_recompute and self.training:
                    out = recompute(blk, x, policy=self.cfg.recompute_policy)
                else:
                    out = blk(x)
                if is_moe:
                    x, vec = out
                    vecs.append(vec)
                else:
                    x = out
            if moe_stack and vecs:
                from ..tensor.manipulation import stack as tstack
                self.__dict__["_moe_vecs"] = tstack(vecs, axis=0)
        if moe_stack:
            self._reduce_moe_loss()
        x = self.final_norm(x)
        return x if caches is None else (x, new_caches)

    # -- MoE side channel --------------------------------------------------
    def _reduce_moe_loss(self):
        """Weighted router losses of the last no-cache forward: aux (load
        balance) + z (logit magnitude), summed over MoE layers. Same-trace
        value — consume it in the SAME loss computation that ran the
        forward (TrainStep loss_fns do)."""
        vecs = self.__dict__.get("_moe_vecs")
        if vecs is None:
            self.__dict__["_moe_loss"] = None
            return
        w_a = float(self.cfg.moe_aux_weight)
        w_z = float(self.cfg.moe_z_weight)
        self.__dict__["_moe_loss"] = apply(
            lambda v: (w_a * v[:, 0].sum()
                       + w_z * v[:, 1].sum()).astype(jnp.float32),
            vecs, name="gpt_moe_loss")

    def moe_loss(self):
        """Weighted MoE router loss (aux + z) of the last forward, or
        None for dense configs. Add it to the CE in the loss_fn:
        ``crit(logits, labels) + model.gpt.moe_loss()``."""
        return self.__dict__.get("_moe_loss")

    def moe_layer_stats(self):
        """Per-MoE-layer router vectors [L_moe, 5+E] of the last no-cache
        forward (Tensor), or None. Rows follow
        ``cfg.moe_layer_indices()`` order; columns are [aux, z, drop,
        entropy, balance, load_0..E-1]."""
        return self.__dict__.get("_moe_vecs")

    def publish_moe_telemetry(self, registry=None) -> int:
        """Publish per-layer router gauges (balance/drop/entropy/loads)
        from the last EAGER forward into the monitor registry; returns
        the number of layers published (0 when the last forward was
        traced — run one eager forward to harvest).
        tools/monitor_report.py --moe renders the result."""
        import jax as _jax
        import numpy as np
        vecs = self.__dict__.get("_moe_vecs")
        if vecs is None or isinstance(vecs._data, _jax.core.Tracer):
            from ..incubate.moe import publish_router_stats
            return publish_router_stats(self, registry)
        from ..incubate.moe.layer import _publish_row
        arr = np.asarray(vecs._data)
        E = self.cfg.moe_experts
        for row, i in zip(arr, self.cfg.moe_layer_indices()):
            _publish_row(row[2:], f"layer{i}", E, registry)
        return arr.shape[0]

    def _forward_paged(self, x, caches, cache_pos):
        """Run the stack over a paged KV view: under scan
        (``FLAGS_scan_decode``, default) each layer's page pools thread
        the one ``lax.scan`` as scanned-over state — decode keeps the
        O(1)-in-depth trace/compile cost of training; the loop layout
        (kill switch / heterogeneous stacks) computes the same math per
        layer."""
        from ..core.flags import get_flag
        from ..serving.kv_cache import (ContextPagedCacheView,
                                        ContextPagedLayerCache,
                                        PagedCacheView, PagedLayerCache)
        # the view CLASS carries the attention-path choice: a
        # ContextPagedCacheView (chunked prefill / prefix-hit tails /
        # speculative verify) selects the gather-over-prior-pages S>1
        # path at trace time (ISSUE 15)
        is_ctx = isinstance(caches, ContextPagedCacheView)
        layer_cls = ContextPagedLayerCache if is_ctx else PagedLayerCache
        body = _paged_scan_body_ctx if is_ctx else _paged_scan_body
        quant = caches.k_scale is not None
        lora = caches.lora_a is not None
        eligible = self.cfg.scan_layers and can_scan_layers(self.layers)
        if eligible and get_flag("scan_decode"):
            cache_arrs = (caches.k, caches.v)
            if quant:
                cache_arrs += (caches.k_scale, caches.v_scale)
            # LoRA pools are [L, ...] per-layer state the decode step
            # READS but never writes: scanned-over inputs, no outputs
            scan_in = (caches.lora_a, caches.lora_b) if lora else ()
            extras = (caches.block_table, cache_pos)
            if lora:
                extras += (caches.lora_ids,)
            x, new = scan_layers_with_cache(
                self.layers, x, cache_arrs, *extras,
                body_call=body, scan_in=scan_in, name="gpt_paged_scan")
            x = self.final_norm(x)
            if quant:
                return x, PagedCacheView(new[0], new[1],
                                         caches.block_table,
                                         new[2], new[3])
            return x, PagedCacheView(new[0], new[1], caches.block_table)
        if eligible:
            note_scan_fallback("scan_decode_disabled", "gpt")
        from ..tensor.manipulation import stack as tstack
        ks, vs, kscs, vscs = [], [], [], []
        for i, blk in enumerate(self.layers):
            layer_cache = layer_cls(
                caches.k[i], caches.v[i], caches.block_table,
                caches.k_scale[i] if quant else None,
                caches.v_scale[i] if quant else None,
                caches.lora_a[i] if lora else None,
                caches.lora_b[i] if lora else None,
                caches.lora_ids if lora else None)
            x, c = blk(x, layer_cache, pos=cache_pos)
            ks.append(c.k_pages)
            vs.append(c.v_pages)
            if quant:
                kscs.append(c.k_scale)
                vscs.append(c.v_scale)
        x = self.final_norm(x)
        if quant:
            return x, PagedCacheView(
                tstack(ks, axis=0), tstack(vs, axis=0),
                caches.block_table,
                tstack(kscs, axis=0), tstack(vscs, axis=0))
        return x, PagedCacheView(tstack(ks, axis=0), tstack(vs, axis=0),
                                 caches.block_table)


def parallel_logits(hidden, embedding_weight):
    """LM head: hidden @ W_vocab.T with the vocab axis kept mp-sharded.

    reference: parallel_matmul in the reference GPT impls — a column-parallel
    matmul against the tied embedding table followed by NO gather; the
    vocab-sharded logits feed ParallelCrossEntropy."""
    prec = matmul_precision()

    def fn(h, w):
        return jnp.einsum("bse,ve->bsv", h, w, precision=prec)

    logits = apply(fn, hidden, embedding_weight, name="lm_logits")
    return _constrain(logits, BATCH, None, MP)


class GPTPretrainingCriterion(Layer):
    """Mean vocab-parallel CE over non-masked positions.

    reference: c_softmax_with_cross_entropy_op.cu + the loss-mask mean."""

    def __init__(self):
        super().__init__()
        self.ce = ParallelCrossEntropy()

    def forward(self, logits, labels, loss_mask=None):
        losses = self.ce(logits, labels)          # [B, S, 1]
        from ..tensor.manipulation import squeeze
        losses = squeeze(losses, -1)

        def reduce_fn(ls, *mm):
            ls = ls.astype(jnp.float32)
            if mm:
                m = mm[0].astype(jnp.float32)
                return jnp.sum(ls * m) / jnp.maximum(jnp.sum(m), 1.0)
            return jnp.mean(ls)

        args = [losses] + ([loss_mask] if loss_mask is not None else [])
        return apply(reduce_fn, *args, name="masked_lm_mean")


class GPTForPretraining(Layer):
    """GPT with the tied vocab-parallel LM head."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.gpt = GPTModel(cfg)

    def forward(self, input_ids, position_ids=None, caches=None,
                cache_pos=None):
        out = self.gpt(input_ids, position_ids, caches, cache_pos=cache_pos)
        if caches is not None:
            hidden, new_caches = out
            return parallel_logits(hidden, self.gpt.word_embeddings.weight), \
                new_caches
        return parallel_logits(out, self.gpt.word_embeddings.weight)

    def moe_loss(self):
        """Weighted MoE router loss of the last forward (see
        GPTModel.moe_loss), or None for dense configs."""
        return self.gpt.moe_loss()

    def generate(self, input_ids, max_new_tokens=32, **kwargs):
        """Autoregressive decoding with a static KV cache (see
        models/generation.py)."""
        from .generation import generate
        return generate(self, input_ids, max_new_tokens=max_new_tokens,
                        **kwargs)


def gpt_tiny(**kw) -> GPTConfig:
    """Test-size config (runs on CPU meshes in seconds)."""
    d = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
             max_position_embeddings=128, hidden_dropout_prob=0.0,
             attention_dropout_prob=0.0)
    d.update(kw)
    return GPTConfig(**d)


def gpt2_small(**kw) -> GPTConfig:
    d = dict(vocab_size=50304, hidden_size=768, num_layers=12, num_heads=12,
             max_position_embeddings=1024)
    d.update(kw)
    return GPTConfig(**d)


def gpt2_large(**kw) -> GPTConfig:
    d = dict(vocab_size=50304, hidden_size=1280, num_layers=36,
             num_heads=20, max_position_embeddings=1024)
    d.update(kw)
    return GPTConfig(**d)


def gpt2_xl(**kw) -> GPTConfig:
    d = dict(vocab_size=50304, hidden_size=1600, num_layers=48,
             num_heads=25, max_position_embeddings=1024)
    d.update(kw)
    return GPTConfig(**d)


def gpt2_medium(**kw) -> GPTConfig:
    """GPT-2 345M — BASELINE.md config 4."""
    d = dict(vocab_size=50304, hidden_size=1024, num_layers=24, num_heads=16,
             max_position_embeddings=1024)
    d.update(kw)
    return GPTConfig(**d)


# ---------------------------------------------------------------------------
# Pipeline-parallel GPT (BASELINE config 4: GPT-2 345M PP + TP)
# ---------------------------------------------------------------------------


class GPTForPretrainingPipe(Layer):
    """GPT with the decoder stack as an SPMD pipeline over the ``pp`` mesh
    axis (BASELINE config 4: PP + TP).

    reference: the model-zoo GPTForPretrainingPipe over
    fleet/meta_parallel/pipeline_parallel.py. TPU-native: the N decoder
    blocks live in a :class:`PipelineStageStack` — layer-stacked params
    sharded over ``pp``, one scan+ppermute program (see spmd_pipeline.py);
    embeddings/final-norm/tied head stay outside the pipeline, replicated
    over ``pp`` and sharded over ``mp``/data axes by GSPMD exactly as in
    GPTForPretraining. TP composes *inside* each stage because the
    pipeline's shard_map is manual only over ``pp``.

    Degrades to sequential execution (same params, same math) when no mesh
    or pp degree 1 is active.
    """

    def __init__(self, cfg: GPTConfig,
                 num_microbatches: Optional[int] = None,
                 schedule: Optional[str] = None):
        super().__init__()
        from ..distributed.meta_parallel.spmd_pipeline import (
            PipelineStageStack)
        if cfg.moe_experts:
            raise NotImplementedError(
                "GPTForPretrainingPipe does not support MoE configs yet "
                "(the pipeline stage stack builds dense decoder layers); "
                "use GPTForPretraining — MoE composes with DP/EP/TP, the "
                "pp schedule is an open item (docs/MOE.md)")
        self.cfg = cfg
        self.word_embeddings = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size)
        self.word_embeddings.weight._data = Normal(
            0.0, cfg.initializer_range)(
            (cfg.vocab_size, cfg.hidden_size), "float32")
        self.position_embeddings = Embedding(
            cfg.max_position_embeddings, cfg.hidden_size)
        self.position_embeddings.weight._data = Normal(
            0.0, cfg.initializer_range)(
            (cfg.max_position_embeddings, cfg.hidden_size), "float32")
        self.embedding_dropout = Dropout(cfg.hidden_dropout_prob)
        self.blocks = PipelineStageStack(
            lambda: GPTDecoderLayer(cfg), cfg.num_layers,
            num_microbatches=num_microbatches, schedule=schedule)
        self.final_norm = LayerNorm(cfg.hidden_size)

    def _embed(self, input_ids, position_ids=None):
        S = input_ids.shape[1]
        if position_ids is None:
            from ..tensor.creation import arange
            position_ids = arange(0, S, dtype="int32")
        x = self.word_embeddings(input_ids) + \
            self.position_embeddings(position_ids)
        x = self.embedding_dropout(x)
        sp = _seq_spec(self.cfg)
        if sp:
            x = _constrain(x, BATCH, sp, None)
        return x

    def forward(self, input_ids, position_ids=None):
        x = self.blocks(self._embed(input_ids, position_ids))
        x = self.final_norm(x)
        return parallel_logits(x, self.word_embeddings.weight)

    def _head_apply(self):
        """The pipeline loss head as a raw-array function over explicit
        leaves — final LayerNorm -> tied vocab-parallel logits -> masked
        CE (loss_sum, mask_sum). The SAME math as
        forward()+GPTPretrainingCriterion, packaged so the 1F1B schedule
        can run it per microbatch on the last stage (and the fill-drain
        path on the full batch) — schedule parity by construction."""
        cached = self.__dict__.get("_head_apply_fn")
        if cached is not None:
            return cached
        from ..core.tensor import Tensor
        from ..jit.functional import bind
        norm = self.final_norm
        norm_names = [n for n, _ in norm.named_parameters()]
        ce = ParallelCrossEntropy()

        def head_apply(leaves, y, lab, msk):
            with bind(norm, dict(zip(norm_names, leaves))):
                h = norm(Tensor(y))
            logits = parallel_logits(h, Tensor(leaves[len(norm_names)]))
            losses = ce(logits, Tensor(lab))
            ls = losses._data if isinstance(losses, Tensor) else losses
            ls = jnp.squeeze(ls, -1).astype(jnp.float32)
            m = msk.astype(jnp.float32)
            return jnp.sum(ls * m), jnp.sum(m)

        self.__dict__["_head_apply_fn"] = head_apply
        return head_apply

    def pretraining_loss(self, input_ids, labels, loss_mask=None,
                         position_ids=None):
        """Schedule-aware pretraining loss: embeddings ->
        ``PipelineStageStack.train_loss`` (1F1B combined program on
        capable pp meshes, fill-drain otherwise) -> masked-mean CE.
        Numerically equivalent to
        ``GPTPretrainingCriterion()(self(ids), labels, loss_mask)`` up to
        the per-microbatch summation order (pinned at 1e-6)."""
        from ..core.tensor import Tensor
        x = self._embed(input_ids, position_ids)
        if loss_mask is None:
            ones = jnp.ones(tuple(labels.shape), jnp.float32)
            loss_mask = Tensor(ones)
        head_leaves = [p for _, p in self.final_norm.named_parameters()]
        head_leaves.append(self.word_embeddings.weight)
        return self.blocks.train_loss(
            x, self._head_apply(), head_leaves, [labels, loss_mask],
            head_token=("gpt_pipe_head", id(self)))


class _GPTEmbeddingStage(Layer):
    """Embedding front of the pipeline: ids -> hidden states."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.word_embeddings = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size)
        self.word_embeddings.weight._data = Normal(
            0.0, cfg.initializer_range)(
            (cfg.vocab_size, cfg.hidden_size), "float32")
        self.position_embeddings = Embedding(
            cfg.max_position_embeddings, cfg.hidden_size)
        self.position_embeddings.weight._data = Normal(
            0.0, cfg.initializer_range)(
            (cfg.max_position_embeddings, cfg.hidden_size), "float32")
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids):
        from ..tensor.creation import arange
        S = input_ids.shape[1]
        pos = arange(0, S, dtype="int32")
        x = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        return self.dropout(x)


def gpt_pipeline_descs(cfg: GPTConfig):
    """LayerDesc list for PipelineLayer: embedding | N blocks | tied head
    (reference: the model-zoo GPTForPretrainingPipe built on
    fleet/meta_parallel/parallel_layers/pp_layers.py LayerDesc/
    SharedLayerDesc with shared embedding between first/last stage)."""
    from ..distributed.meta_parallel.parallel_layers.pp_layers import (
        LayerDesc, SharedLayerDesc)

    def embed_fwd(shared, ids):
        return shared(ids)

    def head_fwd(shared, hidden):
        # tied LM head: project onto the stage-0 embedding table (the
        # final LayerNorm is its own desc just before this one)
        return parallel_logits(hidden, shared.word_embeddings.weight)

    descs = [
        SharedLayerDesc("gpt_embed", _GPTEmbeddingStage,
                        forward_func=embed_fwd, cfg=cfg),
    ]
    descs += [LayerDesc(GPTDecoderLayer, cfg) for _ in range(cfg.num_layers)]
    descs.append(LayerDesc(LayerNorm, cfg.hidden_size))
    descs.append(SharedLayerDesc("gpt_embed", _GPTEmbeddingStage,
                                 forward_func=head_fwd, cfg=cfg))
    return descs


def build_gpt_pipe(cfg: GPTConfig, num_stages: int, accumulate_steps: int = 1,
                   seg_method: str = "uniform"):
    """GPT as a PipelineParallel engine (PP outer, TP inner via the
    vocab/column/row-parallel layers inside each desc)."""
    from ..distributed.meta_parallel.parallel_layers.pp_layers import (
        PipelineLayer)
    from ..distributed.meta_parallel.pipeline_parallel import (
        PipelineParallel)

    crit = GPTPretrainingCriterion()

    def loss_fn(logits, labels):
        return crit(logits, labels)

    pl_layer = PipelineLayer(gpt_pipeline_descs(cfg), num_stages=num_stages,
                             loss_fn=loss_fn, seg_method=seg_method)
    return PipelineParallel(pl_layer, accumulate_steps=accumulate_steps)
