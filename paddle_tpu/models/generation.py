"""Autoregressive generation with a static KV cache.

reference parity: the decoding surface the reference ecosystem exposes
over these models (greedy / top-k / top-p sampling with growing KV
caches; beam search lives in nn.BeamSearchDecoder). The reference's
dygraph caches grow by concat each step
(nn/layer/transformer.py MultiHeadAttention.Cache, gen_cache).

TPU-native redesign: generation compiles to exactly TWO XLA programs —
a prefill (prompt forward writing K/V into preallocated
[B, prompt+max_new, H, D] buffers) and ONE `lax.scan` over the decode
steps (single-token forward via dynamic_update_slice at `pos`, masked
attention over the static buffers). No per-step retrace, no growing
shapes, no host round-trips inside the loop.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.random import trace_rng
from ..core.tensor import Tensor, no_grad
from ..jit.functional import bind, buffer_arrays, param_arrays

__all__ = ["generate"]


def _sample(logits, key, decode_strategy, temperature, top_k, top_p):
    """Next-token choice from [B, V] logits."""
    if decode_strategy == "greedy_search":
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    lg = logits.astype(jnp.float32) / jnp.maximum(temperature, 1e-6)
    V = lg.shape[-1]
    if top_k and 0 < top_k < V:
        kth = jnp.sort(lg, axis=-1)[:, V - top_k][:, None]
        lg = jnp.where(lg < kth, -1e30, lg)
    if top_p < 1.0:
        sorted_lg = jnp.sort(lg, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_lg, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_lg, cutoff_idx[:, None],
                                     axis=-1)
        lg = jnp.where(lg < cutoff, -1e30, lg)
    return jax.random.categorical(key, lg, axis=-1).astype(jnp.int32)


def generate(model, input_ids, max_new_tokens: int = 32,
             decode_strategy: str = "sampling", temperature: float = 1.0,
             top_k: int = 0, top_p: float = 1.0,
             eos_token_id: Optional[int] = None,
             pad_token_id: int = 0, seed: int = 0):
    """Generate continuations for a batch of prompts.

    model: a GPT-style Layer whose forward accepts
    ``(input_ids, caches=<list of StaticCache>, cache_pos=<scalar>)`` and
    returns ``(logits, caches)``.
    input_ids: [B, S0] int array/Tensor (fixed-shape prompts).
    Returns ids [B, S0 + max_new_tokens] (int32); positions after an
    eos are filled with ``pad_token_id``.
    """
    from .gpt import GPTAttention

    if decode_strategy not in ("greedy_search", "sampling"):
        raise ValueError(
            f"unknown decode_strategy {decode_strategy!r}: use "
            "'greedy_search' or 'sampling' (beam search lives in "
            "paddle.nn.BeamSearchDecoder + dynamic_decode)")
    raw = input_ids._data if isinstance(input_ids, Tensor) else \
        jnp.asarray(np.asarray(input_ids))
    raw = raw.astype(jnp.int32)
    B, S0 = raw.shape
    L = S0 + int(max_new_tokens)
    cfg = model.cfg
    if L > cfg.max_position_embeddings:
        raise ValueError(
            f"prompt ({S0}) + max_new_tokens ({max_new_tokens}) = {L} "
            f"exceeds max_position_embeddings="
            f"{cfg.max_position_embeddings}; positions past the table "
            "would silently clamp")
    H, D = cfg.num_heads, cfg.head_dim
    was_training = model.training
    model.eval()
    params = param_arrays(model)
    buffers = buffer_arrays(model)

    def fresh_caches():
        return [GPTAttention.StaticCache(
            jnp.zeros((B, L, H, D), jnp.float32),
            jnp.zeros((B, L, H, D), jnp.float32))
            for _ in range(cfg.num_layers)]

    def fwd(p, ids, caches, pos):
        with bind(model, p, dict(buffers)), no_grad(), \
                trace_rng(jax.random.key(0)):
            logits, new_caches = model(
                Tensor(ids),
                caches=[GPTAttention.StaticCache(Tensor(c.k), Tensor(c.v))
                        for c in caches],
                cache_pos=Tensor(pos))
        return (logits._data,
                [GPTAttention.StaticCache(c.k._data, c.v._data)
                 for c in new_caches])

    cache_key = (B, S0, int(max_new_tokens), decode_strategy,
                 float(temperature), int(top_k), float(top_p),
                 eos_token_id, pad_token_id)
    # compiled programs live ON the model (a closure over the model stored
    # in any global map would pin the model alive; an attribute is just a
    # collectible reference cycle)
    compiled = getattr(model, "_gen_compiled", None)
    if compiled is None:
        compiled = {}
        object.__setattr__(model, "_gen_compiled", compiled)
    run = compiled.get(cache_key)
    if run is not None:
        try:
            out = run(params, raw, jax.random.key(seed))
        finally:
            if was_training:
                model.train()
        return Tensor(out)

    @jax.jit
    def run(p, prompt, key):
        caches = fresh_caches()
        zero = jnp.asarray(0, jnp.int32)
        logits, caches = fwd(p, prompt, caches, zero)
        last = logits[:, -1, :]
        key, sub = jax.random.split(key)
        tok = _sample(last, sub, decode_strategy, temperature, top_k,
                      top_p)
        finished = jnp.zeros((B,), bool) if eos_token_id is None else \
            (tok == eos_token_id)

        def step(carry, key_t):
            caches, tok, pos, finished = carry
            logits, caches = fwd(p, tok[:, None], caches, pos)
            nxt = _sample(logits[:, -1, :], key_t, decode_strategy,
                          temperature, top_k, top_p)
            if eos_token_id is not None:
                nxt = jnp.where(finished, pad_token_id, nxt)
                finished = finished | (nxt == eos_token_id)
            return (caches, nxt, pos + 1, finished), nxt

        if max_new_tokens == 1:
            return jnp.concatenate([prompt, tok[:, None]], axis=1)
        keys = jax.random.split(key, max_new_tokens - 1)
        (_, _, _, _), toks = jax.lax.scan(
            step, (caches, tok, jnp.asarray(S0, jnp.int32), finished),
            keys)
        return jnp.concatenate([prompt, tok[:, None],
                                jnp.moveaxis(toks, 0, 1)], axis=1)

    compiled[cache_key] = run
    try:
        out = run(params, raw, jax.random.key(seed))
    finally:
        if was_training:
            model.train()
    return Tensor(out)
