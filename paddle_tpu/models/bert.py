"""BERT: bidirectional encoder + MLM head (BASELINE.md config 3).

reference parity: the reference's BERT family is built on
nn/layer/transformer.py TransformerEncoder(:~900) with fused attention
(fused_attention_op.cu) underneath; MLM pretraining mirrors
model_zoo/bert semantics (masked positions gathered, CE over vocab).

TPU-native: the encoder reuses nn.TransformerEncoder (whose attention
dispatches to the Pallas flash kernel when eligible); the MLM loss gathers
masked positions with a static-shape `take_along_axis` so the whole step
stays jit-compilable (no dynamic boolean indexing).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.flags import matmul_precision
from ..core.tensor import Tensor, apply
from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.layer import Layer
from ..nn.layers.common import Dropout, Embedding
from ..nn.layers.norm import LayerNorm
from ..nn.layers.transformer import TransformerEncoder, TransformerEncoderLayer

__all__ = ["BertConfig", "BertModel", "BertForMaskedLM", "bert_tiny",
           "bert_base", "bert_large"]


@dataclass
class BertConfig:
    vocab_size: int = 30528          # padded to a multiple of 64
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    #: run the encoder stack as one jax.lax.scan over layer-stacked params
    #: (nn.scan; O(1) trace/compile in num_layers, state_dict unchanged)
    scan_layers: bool = True
    use_recompute: bool = False
    #: selective-remat policy name (fleet.utils.recompute.
    #: resolve_checkpoint_policy); None = full remat
    recompute_policy: Optional[str] = None


class BertEmbeddings(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        init = Normal(0.0, cfg.initializer_range)
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.word_embeddings.weight._data = init(
            (cfg.vocab_size, cfg.hidden_size), "float32")
        self.position_embeddings = Embedding(cfg.max_position_embeddings,
                                             cfg.hidden_size)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size,
                                               cfg.hidden_size)
        self.layer_norm = LayerNorm(cfg.hidden_size)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        S = input_ids.shape[1]
        if position_ids is None:
            from ..tensor.creation import arange
            position_ids = arange(0, S, dtype="int32")
        x = self.word_embeddings(input_ids) + \
            self.position_embeddings(position_ids)
        if token_type_ids is not None:
            x = x + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(x))


class BertModel(Layer):
    """Embeddings + post-LN transformer encoder + tanh pooler."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        enc_layer = TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout_prob, activation="gelu",
            attn_dropout=cfg.attention_dropout_prob,
            act_dropout=0.0, normalize_before=False)
        self.encoder = TransformerEncoder(enc_layer, cfg.num_layers)
        self.encoder.enable_scan = cfg.scan_layers
        self.encoder.use_recompute = cfg.use_recompute
        self.encoder.recompute_policy = cfg.recompute_policy
        from ..nn.layers.common import Linear
        self.pooler = Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                position_ids=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        if attention_mask is not None and attention_mask.ndim == 2:
            # [B, S] 1/0 mask -> additive [B, 1, 1, S]
            def to_additive(m):
                return ((1.0 - m.astype(jnp.float32))
                        * -1e30)[:, None, None, :]
            attention_mask = apply(to_additive, attention_mask,
                                   name="bert_attn_mask")
        seq = self.encoder(x, attention_mask)
        pooled = F.tanh(self.pooler(seq[:, 0]))
        return seq, pooled


class BertForMaskedLM(Layer):
    """BERT + transform head + tied decoder over the vocab."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.bert = BertModel(cfg)
        from ..nn.layers.common import Linear
        self.transform = Linear(cfg.hidden_size, cfg.hidden_size)
        self.transform_norm = LayerNorm(cfg.hidden_size)
        self.decoder_bias = self.create_parameter((cfg.vocab_size,),
                                                  is_bias=True)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_positions=None):
        seq, _ = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.transform_norm(F.gelu(self.transform(seq), approximate=True))
        w = self.bert.embeddings.word_embeddings.weight
        prec = matmul_precision()

        def head(hh, ww, bb, *mp):
            if mp:
                # gather masked positions (static count) before the big gemm
                idx = mp[0].astype(jnp.int32)               # [B, M]
                hh = jnp.take_along_axis(hh, idx[..., None], axis=1)
            return jnp.einsum("bme,ve->bmv", hh, ww, precision=prec) + bb

        args = [h, w, self.decoder_bias] + (
            [masked_positions] if masked_positions is not None else [])
        return apply(head, *args, name="mlm_head")

    def loss(self, prediction_scores, masked_lm_labels, masked_lm_weights=None):
        """Mean CE over masked positions; labels [B, M], weights [B, M].

        Above the chunked-CE vocab threshold the logsumexp streams over
        vocab chunks (nn/chunked_ce.py — online f32 accumulation, no
        full-vocab f32 log-probs); below it the dense composition runs."""
        from ..nn import chunked_ce as _cce
        chunked = _cce.enabled_for(prediction_scores.shape[-1])

        def ce(lg, lab, *ww):
            return _cce.masked_lm_loss(lg, lab, *ww, chunked=chunked)

        args = [prediction_scores, masked_lm_labels] + (
            [masked_lm_weights] if masked_lm_weights is not None else [])
        return apply(ce, *args, name="mlm_loss")


def bert_tiny(**kw) -> BertConfig:
    d = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
             intermediate_size=128, max_position_embeddings=128,
             hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    d.update(kw)
    return BertConfig(**d)


def bert_large(**kw) -> BertConfig:
    d = dict(hidden_size=1024, num_layers=24, num_heads=16,
             intermediate_size=4096)
    d.update(kw)
    return BertConfig(**d)


def bert_base(**kw) -> BertConfig:
    d = dict()
    d.update(kw)
    return BertConfig(**d)
