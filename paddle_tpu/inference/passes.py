"""Inference graph passes (the XLA-era analogue of the reference's
inference/analysis IR passes).

On TPU most "passes" are the XLA compiler; what remains profitable at the
framework level is WEIGHT transformations that XLA cannot do because they
change the parameter values themselves. The classic one for vision
deployments is conv+BN folding (reference analogue:
inference/analysis/passes + the conv_bn_fuse_pass of framework/ir): at
inference time BatchNorm is an affine map with frozen statistics, so it
folds into the preceding conv's weight and bias exactly:

    w' = w * gamma / sqrt(var + eps)        (per out-channel)
    b' = beta + (b - mean) * gamma / sqrt(var + eps)

after which the BN layer is replaced with Identity — one conv kernel, no
separate normalization traffic, and the epilogue fusion has nothing left
to fuse because the work is gone.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["fold_conv_bn"]


def _fold_containers():
    """Container types where child-declaration adjacency IS dataflow
    adjacency, so the fold is provably safe: Sequential bodies run children
    in order, and the vision zoo's blocks wire convN straight into bnN in
    forward. An arbitrary user Layer may declare a conv next to a BN it
    never feeds (parallel branches) — folding there would silently corrupt
    both branches, so it is excluded from the default pass."""
    from ..nn.layer import Sequential
    from ..vision.models.resnet import (BasicBlock, BottleneckBlock,
                                        ResNet)
    return (Sequential, ResNet, BasicBlock, BottleneckBlock)


def fold_conv_bn(layer, aggressive: bool = False) -> int:
    """Fold every (Conv2D, BatchNorm) pair of adjacent children into the
    conv, replacing the BN with Identity. Recurses through the whole layer
    tree; pairs are folded only inside containers whose declaration order
    is known to match dataflow (Sequential + the vision zoo blocks) unless
    ``aggressive=True`` extends the fold to every adjacent pair.

    Mutates ``layer`` in place (call on an eval-mode copy for deployment);
    returns the number of folded pairs.
    """
    from ..core.tensor import Parameter, Tensor
    from ..nn.layers.common import Identity
    from ..nn.layers.conv import Conv2D
    from ..nn.layers.norm import SyncBatchNorm, _BatchNormBase

    folded = 0
    children = list(layer._sub_layers.items())
    fold_here = aggressive or isinstance(layer, _fold_containers())
    for (name_a, a), (name_b, b) in zip(children, children[1:]):
        if not fold_here:
            break
        if not (type(a) is Conv2D and isinstance(b, _BatchNormBase)
                and not isinstance(b, SyncBatchNorm)):
            continue
        gamma = b.weight._data.astype(jnp.float32) if b.weight is not None \
            else jnp.ones_like(b._mean._data)
        beta = b.bias._data.astype(jnp.float32) if b.bias is not None \
            else jnp.zeros_like(b._mean._data)
        mean = b._mean._data.astype(jnp.float32)
        var = b._variance._data.astype(jnp.float32)
        scale = gamma / jnp.sqrt(var + b._epsilon)
        w = a.weight._data
        # conv weight layout is [out_c, in_c/groups, kh, kw]: scale over
        # the out-channel axis
        new_w = (w.astype(jnp.float32)
                 * scale.reshape((-1,) + (1,) * (w.ndim - 1))).astype(w.dtype)
        old_b = a.bias._data.astype(jnp.float32) if a.bias is not None \
            else jnp.zeros_like(mean)
        new_b = beta + (old_b - mean) * scale
        a.weight._data = new_w
        if a.bias is not None:
            a.bias._data = new_b.astype(a.bias._data.dtype)
        else:
            a.bias = Parameter(Tensor(new_b), trainable=False)
        layer._sub_layers[name_b] = Identity()
        folded += 1
    for child in layer._sub_layers.values():
        folded += fold_conv_bn(child, aggressive=aggressive)
    return folded
