"""paddle.inference: Config + create_predictor deployment API.

reference parity: the inference engine surface —
`paddle.inference.Config` / `create_predictor` bound from
pybind/inference_api.cc over AnalysisPredictor
(reference: paddle/fluid/inference/api/analysis_predictor.cc:151
Init, :411 Run; analysis passes in inference/analysis/), with the
zero-copy handle API (get_input_handle / copy_from_cpu / run /
get_output_handle / copy_to_cpu).

TPU-native redesign: the reference's analysis/IR pass pipeline IS the
XLA compiler here — a jit.save export is already a fused, laid-out TPU
executable, so "optimization passes" reduce to choices made when the
predictor is built:
 - from a jit.save path: load the serialized executable and run it
   (nothing to optimize — XLA did it at export);
 - from a live Layer: apply the requested passes (bf16 weight cast,
   int8 weight-only quantization via paddle_tpu.slim) and jit with
   donated buffers; `save_optimized_model` re-exports the optimized
   form for later zero-work loads.
"""

from __future__ import annotations

import warnings
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Config", "Predictor", "create_predictor",
           "create_serving_engine", "PrecisionType"]


class PrecisionType:
    Float32 = "float32"
    Bfloat16 = "bfloat16"
    Half = "float16"
    Int8 = "int8"


class Config:
    """Predictor configuration (reference: inference_api.cc Config).

    Construct from a jit.save path prefix (`Config("dir/model")` with
    dir/model.jaxexport + .pdiparams on disk), or from a live layer via
    `Config.from_layer(layer, input_spec=[...])`.
    """

    def __init__(self, model_path: Optional[str] = None,
                 params_path: Optional[str] = None):
        # params_path kept for API parity; the jit.save bundle is
        # addressed by one prefix
        self.model_path = model_path
        self.params_path = params_path
        self.layer = None
        self.input_spec = None
        self._precision = PrecisionType.Float32
        self._weight_quant = False
        self._ir_optim = True
        self._memory_optim = True

    @classmethod
    def from_layer(cls, layer, input_spec) -> "Config":
        cfg = cls()
        cfg.layer = layer
        cfg.input_spec = list(input_spec)
        return cfg

    # -- optimization switches (reference Config surface) ----------------
    def enable_tpu_bf16(self):
        """Run matmul-class compute in bf16 (the analogue of
        enable_mkldnn_bfloat16 / TRT fp16: the TPU MXU's fast path)."""
        self._precision = PrecisionType.Bfloat16

    def enable_int8(self):
        """int8 quantization (analogue of TRT int8; needs a live layer —
        a serialized executable is already frozen). Weights are stored
        per-channel int8 and — with ``FLAGS_pallas_int8`` (default) —
        STAY int8 through the matmul: the Pallas int8 kernel quantizes
        the activation stream per tensor and runs int8 x int8 -> int32
        on the MXU (ops.pallas.quant_matmul). With the kill switch off
        the pre-kernel behavior returns: weights dequantize into a
        float gemm."""
        self._weight_quant = True

    def switch_ir_optim(self, flag: bool = True):
        self._ir_optim = bool(flag)

    def enable_memory_optim(self, flag: bool = True):
        self._memory_optim = bool(flag)

    # parity no-ops: XLA owns these decisions on TPU
    def set_cpu_math_library_num_threads(self, n: int):
        pass

    def disable_glog_info(self):
        pass

    def summary(self) -> str:
        src = self.model_path or f"layer:{type(self.layer).__name__}"
        return (f"source: {src}\nprecision: {self._precision}\n"
                f"weight_quant: {self._weight_quant}")


class _Handle:
    """Zero-copy style input/output handle (reference: ZeroCopyTensor)."""

    def __init__(self, name: str, shape=None):
        self.name = name
        self._shape = tuple(shape) if shape else None
        self._value: Optional[np.ndarray] = None

    def reshape(self, shape: Sequence[int]):
        self._shape = tuple(shape)

    def copy_from_cpu(self, arr: np.ndarray):
        self._value = np.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        if self._value is None:
            raise RuntimeError("run() has not produced this output yet")
        return np.asarray(self._value)

    def shape(self):
        return self._shape if self._value is None else self._value.shape


class Predictor:
    """Runs a frozen model: the AnalysisPredictor analogue."""

    def __init__(self, config: Config):
        self._config = config
        self._inputs: Dict[str, _Handle] = {}
        self._outputs: Dict[str, _Handle] = {}
        self._out_names: List[str] = []
        if config.layer is not None:
            self._init_from_layer(config)
        elif config.model_path is not None:
            self._init_from_export(config)
        else:
            raise ValueError("Config needs a model path or a layer")

    # -- construction ----------------------------------------------------
    def _init_from_export(self, config: Config):
        from ..jit.to_static import load as jload
        translated = jload(config.model_path)
        if isinstance(translated, dict):
            raise ValueError(
                f"{config.model_path!r} is a weights-only save (no "
                ".jaxexport executable); re-save with input_spec or use "
                "Config.from_layer")
        if config._weight_quant or \
                config._precision != PrecisionType.Float32:
            warnings.warn(
                "a serialized executable is already compiled; precision/"
                "quantization options apply only to Config.from_layer",
                stacklevel=3)
        self._runner = translated
        spec = translated._meta.get("input_spec") or []
        for i, (shape, dtype) in enumerate(spec):
            self._inputs[f"x{i}"] = _Handle(f"x{i}", shape)

    def _init_from_layer(self, config: Config):
        from ..core.random import trace_rng
        from ..core.tensor import Tensor, no_grad
        from ..jit.functional import bind, buffer_arrays, param_arrays
        from ..jit.input_spec import InputSpec

        layer = config.layer
        layer.eval()
        if config._ir_optim:
            # conv+BN weight folding: the one IR-level optimization XLA
            # cannot perform (it rewrites parameter VALUES); see passes.py
            from .passes import fold_conv_bn
            fold_conv_bn(layer)
        if config._weight_quant:
            from ..slim import quantize_weights
            quantize_weights(layer)
        params = param_arrays(layer)
        buffers = buffer_arrays(layer)
        if config._precision == PrecisionType.Bfloat16:
            params = {k: v.astype(jnp.bfloat16)
                      if jnp.issubdtype(v.dtype, jnp.floating) else v
                      for k, v in params.items()}

        specs = [s if isinstance(s, InputSpec) else InputSpec(s)
                 for s in config.input_spec]

        bf16 = config._precision == PrecisionType.Bfloat16

        def pure(p, b, *inputs):
            if bf16:
                # the activation stream must match the cast weights (conv
                # ops require one dtype); outputs come back f32 — the
                # standard bf16-compute/f32-results serving contract
                inputs = [i.astype(jnp.bfloat16)
                          if hasattr(i, "dtype") and
                          jnp.issubdtype(i.dtype, jnp.floating) else i
                          for i in inputs]
            with bind(layer, p, dict(b)), no_grad(), \
                    trace_rng(jax.random.key(0)):
                out = layer(*[Tensor(i) for i in inputs])
            from ..jit.functional import unwrap
            out = unwrap(out)
            if bf16:
                out = jax.tree_util.tree_map(
                    lambda o: o.astype(jnp.float32)
                    if hasattr(o, "dtype") and
                    jnp.issubdtype(o.dtype, jnp.floating) else o, out)
            return out

        jitted = jax.jit(pure)
        self._runner = lambda *raw: jitted(params, buffers, *raw)
        for i, s in enumerate(specs):
            self._inputs[f"x{i}"] = _Handle(f"x{i}", s.shape)

    # -- reference API surface -------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._inputs)

    def get_input_handle(self, name: str) -> _Handle:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        return list(self._out_names)

    def get_output_handle(self, name: str) -> _Handle:
        return self._outputs[name]

    def run(self, inputs: Optional[Sequence[np.ndarray]] = None):
        """Execute. Either pass arrays directly (returns list of arrays,
        the modern surface) or pre-fill input handles (zero-copy surface:
        results land in the output handles)."""
        if inputs is None:
            vals = []
            for name, h in self._inputs.items():
                if h._value is None:
                    raise RuntimeError(f"input {name!r} not set; call "
                                       "get_input_handle(name)."
                                       "copy_from_cpu(arr) first")
                vals.append(h._value)
        else:
            vals = [np.asarray(v) for v in inputs]
        raw = [jnp.asarray(v) for v in vals]
        out = self._runner(*raw)
        from ..core.tensor import Tensor
        if isinstance(out, Tensor):
            out = out._data
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        outs = [np.asarray(o._data if isinstance(o, Tensor) else o)
                for o in outs]
        self._out_names = [f"out{i}" for i in range(len(outs))]
        self._outputs = {n: _Handle(n) for n in self._out_names}
        for n, o in zip(self._out_names, outs):
            self._outputs[n]._value = o
        return outs if inputs is not None else None

    def save_optimized_model(self, path: str):
        """Persist the (possibly quantized/bf16) layer as a jit.save
        bundle so later loads skip the optimization work
        (reference: the analysis pipeline's optimized-program cache)."""
        if self._config.layer is None:
            raise ValueError("already a serialized executable")
        from ..jit.to_static import save as jsave
        layer = self._config.layer
        if self._config._precision == PrecisionType.Bfloat16:
            # bake the SAME precision the live predictor runs (float
            # params were only cast in the predictor's local copy)
            layer.to(dtype="bfloat16")
        jsave(layer, path, input_spec=self._config.input_spec)


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def create_serving_engine(config_or_layer, serving_config=None):
    """LLM serving entry point: the generation analogue of
    :func:`create_predictor` (reference surface: the inference API over
    AnalysisPredictor — here the continuous-batching engine of
    :mod:`paddle_tpu.serving`, docs/SERVING.md).

    Accepts a live decoder-only Layer (GPT-style ``forward(input_ids,
    caches=..., cache_pos=...)``), or a ``Config.from_layer`` carrying
    weight passes: ``enable_int8()`` applies weight-only quantization to
    the layer, ``enable_tpu_bf16()`` casts the engine's parameter
    snapshot to bf16 (the memory-bound-decode win) before any serving
    program compiles.
    """
    from ..serving import ServingConfig, ServingEngine

    precision = PrecisionType.Float32
    if isinstance(config_or_layer, Config):
        cfg = config_or_layer
        layer = cfg.layer
        if layer is None:
            raise ValueError(
                "create_serving_engine needs a live layer "
                "(Config.from_layer): decode programs are specialized "
                "to the serving bucket table at engine build, not at "
                "jit.save time")
        if cfg._weight_quant:
            from ..slim import quantize_weights
            quantize_weights(layer)
        precision = cfg._precision
    else:
        layer = config_or_layer
    engine = ServingEngine(layer, serving_config or ServingConfig())
    if precision == PrecisionType.Bfloat16:
        # cast the engine's own snapshot (the layer is untouched, same
        # contract as Predictor._init_from_layer); programs compile
        # lazily, so every serving signature sees the bf16 params
        engine.params = {k: v.astype(jnp.bfloat16)
                         if jnp.issubdtype(v.dtype, jnp.floating) else v
                         for k, v in engine.params.items()}
    return engine
