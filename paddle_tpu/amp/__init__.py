from .auto_cast import amp_guard, auto_cast, decorate, white_list, black_list  # noqa: F401
from .grad_scaler import AmpScaler, GradScaler  # noqa: F401
